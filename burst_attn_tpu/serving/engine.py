"""RaggedServeEngine: continuous batching over the one-launch ragged
kernel.

models/serve.py's engine prefills a whole prompt at admission (one
program per prompt page count) and then decodes one token per tick —
a long prompt stalls every in-flight stream for its full prefill.  This
engine schedules PREFILL AS CHUNKS through the same launch that decodes:

  * submit() queues; admission reserves a request's FULL page lifetime
    up front (prompt + budget + speculative slack — mid-generation OOM
    stays impossible by construction) but moves NO tokens.
  * Every tick builds one ragged batch: each mid-prefill slot consumes
    its next `chunk` prompt tokens, each decoding slot its single next
    token, idle slots ride along predicated off.  One
    `ragged_model_step` launch serves them all; a slot whose chunk
    completes its prompt samples its first token THAT tick (TTFT).
  * Speculative decoding is a SCHEDULER POLICY, not a separate engine:
    when a draft model is attached and no slot is mid-prefill, the tick
    becomes a speculative round (k draft proposals per slot, one ragged
    all-logits verify, per-slot prefix acceptance, vectorized rollback).
    Mixed ticks fall back to plain chunking, with the draft cache kept
    in sync through its own ragged catch-up step.
  * Load shedding (`max_queue`): POOL pressure sheds before QUEUE
    pressure — a request that would wait behind others for pages that
    are not free is rejected `pool-exhausted` even when the queue still
    has room; `queue-full` only fires when pages were never the
    bottleneck.  An optional `admission` policy
    (burst_attn_tpu.admission.AdmissionPolicy) sheds EARLY with
    hysteresis from the live queue-depth / pool-occupancy values (typed
    reasons `admission-pool` / `admission-queue`), and every rejection
    is a typed InvalidRequest / LoadShed (`.reason`); `try_submit()` is
    the non-raising router surface.

Kernel routing: `ragged_supported` probes each launch width once; a
declined shape runs the dense-gather fallback and counts a labeled
`burst.fused_fallback{pass="serve"}` — never a raise (ISSUE 8 satellite).

Metrics: every serve.* instrument models/serve.py exports is preserved
(same registry names), plus the `serve.ragged_batch_*` family describing
what each one-launch batch carried (docs/observability.md).
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import trace as tracing
from ..admission import (
    AdmissionPolicy, InvalidRequest, LoadShed, RejectReason, SubmitRejected,
    SubmitResult,
)

logger = obs.get_logger(__name__)

# same instrument names as models/serve.py — the registry get-or-creates,
# so both engines share one catalog and dashboards see one serve.* family
_M_SUBMITTED = obs.counter("serve.requests_submitted")
_M_REJECTED = obs.counter("serve.requests_rejected",
                          "submissions refused up front, by reason")
_M_ADMITTED = obs.counter("serve.requests_admitted")
_M_RETIRED = obs.counter("serve.requests_retired",
                         "finished requests, by cause (eos | budget)")
_M_STEPS = obs.counter("serve.engine_steps")
_M_TOKENS = obs.counter("serve.tokens_generated")
_M_QUEUE = obs.gauge("serve.queue_depth")
_M_LIVE = obs.gauge("serve.live_slots")
_M_POOL = obs.gauge("serve.page_pool_occupancy",
                    "fraction of usable pool pages currently held; also "
                    "published per pool storage dtype under a {dtype} label")
_M_SPEC_RATE = obs.gauge("serve.spec_acceptance_rate")
_M_TTFT = obs.histogram("serve.ttft_s")
_M_TOK_LAT = obs.histogram("serve.token_latency_s")
# host time the tick spent OUTSIDE the device launch+sample window, as a
# fraction of launch-tick wall time (cumulative) — the gap ROADMAP item 3's
# async pipelining is gated against (bench_loadgen emits it as the
# headline_loadgen_hostgap headline).  Always on: host clock reads never
# touch the jaxpr, so the tick's trace stays bit-identical.  On a
# pipelined engine the device window is instead estimated from launch
# dispatch to deferred-readback completion (host work overlapped with a
# busy device is NOT a gap), so the same gauge compares both engines.
_M_HOST_GAP = obs.gauge("serve.host_gap_fraction",
                        "host gap seconds / launch-tick wall seconds")
# pipelined-engine family: speculative schedule divergences and fusion
_M_RECONCILE = obs.counter(
    "serve.pipeline_reconciles",
    "speculatively scheduled pipelined work discarded, by divergence cause")
_M_MULTI = obs.counter(
    "serve.multi_step_launches",
    "fused multi-step decode launches, by static scan depth {k}")
# ragged-batch family: what each one-launch batch carried
_M_RB_LAUNCH = obs.counter("serve.ragged_batch_launches",
                           "one-kernel ragged launches, by batch kind")
_M_RB_PREFILL = obs.counter("serve.ragged_batch_prefill_tokens",
                            "prompt tokens absorbed through ragged launches")
_M_RB_DECODE = obs.counter("serve.ragged_batch_decode_tokens",
                           "decode tokens advanced through ragged launches")
_M_RB_FILL = obs.gauge("serve.ragged_batch_fill",
                       "real-token fraction of the last launch's [slots, "
                       "chunk] token grid")
_M_FALLBACK = obs.counter("burst.fused_fallback")
# prefix-cache family: admission-time sharing and the write barrier
_M_PREFIX_HITS = obs.counter("serve.prefix_hits",
                             "admissions that pinned >= 1 cached prefix page")
_M_PREFIX_MISSES = obs.counter(
    "serve.prefix_misses", "cache-enabled admissions finding no cached prefix")
_M_PAGES_SHARED = obs.counter(
    "serve.pages_shared", "prefix pages pinned (refcount bumped) at admission")
_M_COW = obs.counter("serve.cow_copies",
                     "shared pages privatized by the copy-on-write barrier")
_M_SKIPPED = obs.counter(
    "serve.prefill_tokens_skipped",
    "prompt tokens whose prefill was skipped via cached pages")
_M_POOL_PHYS = obs.gauge(
    "serve.page_pool_occupancy_physical",
    "fraction of usable pool pages physically held (shared pages count "
    "ONCE — identical to serve.page_pool_occupancy)")
_M_POOL_LOG = obs.gauge(
    "serve.page_pool_occupancy_logical",
    "sum of page refcounts over usable pages — may exceed 1.0; the gap to "
    "the physical gauge is the pages saved by prefix sharing")
_M_POOL_BYTES = obs.gauge(
    "serve.page_pool_bytes",
    "HBM bytes physically held by in-use KV pages (k + v + scale banks "
    "across all layers), by pool storage {dtype} — a quantized pool holds "
    "~4x the sequences in the same byte budget")

from ..models.decode import sample_logits
from ..models.paged_decode import (
    PagePool, PagedState, PrefixCache, init_paged_state, paged_decode_step,
    paged_prefill, provision_capacity, retire_slot,
)
from ..models.transformer import ModelConfig
from ..ops.ragged_paged import ragged_supported
from .model import (
    assign_pages, cow_pages, free_slot, free_slots, multi_step_decode,
    pipelined_tick,
    ragged_model_step,
)

# reason-string prefix -> bounded counter label, mirroring
# parallel/burst.py's _FALLBACK_LABELS contract (probe reasons embed
# shapes, which would explode label cardinality verbatim)
_FALLBACK_LABELS = (
    ("empty q chunk", "empty-chunk"),
    ("GQA group mismatch", "gqa-group"),
    ("page size", "page-size"),
    ("q-block rows", "block-rows"),
    ("VMEM plan", "vmem-budget"),
    ("head dim", "head-dim"),
)


def _fallback_label(reason: str) -> str:
    for prefix, label in _FALLBACK_LABELS:
        if reason.startswith(prefix):
            return label
    return "other"


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray          # [T] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)
    t_submit: float = 0.0
    n_prefilled: int = 0        # prompt tokens absorbed so far


def _readback_choices(choices) -> np.ndarray:
    """THE pipeline sync point: block on an in-flight launch's sampled
    choices.  Module-level so the recovery fuzzer can kill the process
    exactly here — after the launch was dispatched, before any of its
    tokens were read back, journaled, or delivered."""
    return np.asarray(choices)


@dataclass
class _Pending:
    """An in-flight pipelined launch whose sampled choices are still on
    device: everything the deferred readback needs to replay the
    synchronous engine's post-sample host accounting one step late."""
    choices: object              # [k, slots] int32 device array
    k: int                       # fused decode depth (1 = plain tick)
    q_lens: np.ndarray           # [slots] per-step token counts
    advance: np.ndarray          # [slots] device length advance (q_lens * k)
    prefill_advance: np.ndarray  # [slots] prompt tokens consumed (k == 1)
    tok_delta: np.ndarray        # [slots] tokens appended at readback
                                 # assuming no EOS fires inside the launch
    rng_before: object           # engine rng before this launch's split(s)
    table_rows: Dict[int, np.ndarray]  # slot -> pre-captured table row for
                                 # prefix registration at readback
    n_prefill_toks: int
    kind: str
    t_dispatch: float
    feed_next: object = None     # [slots] last choice row, sliced at
                                 # dispatch time (enqueued behind the
                                 # launch) so a speculative follow-up
                                 # pays no jnp dispatch in its critical
                                 # pre-dispatch window


class RaggedServeEngine:
    """Host-side continuous-batching loop over ragged_model_step.  Not
    thread-safe; drive it from one thread."""

    def __init__(self, params, cfg: ModelConfig, *, slots: int, n_pages: int,
                 page: int = 128, max_pages_per_seq: int = 64,
                 quantize: bool = False, eos_id: Optional[int] = None,
                 temperature: float = 0.0, top_k=None, top_p=None, rng=None,
                 chunk: Optional[int] = None, max_queue: Optional[int] = None,
                 admission: Optional[AdmissionPolicy] = None,
                 draft_params=None, draft_cfg: Optional[ModelConfig] = None,
                 spec_k: int = 4, use_ragged: Optional[bool] = None,
                 prefix_cache: bool = False, group_attn: bool = True,
                 journal=None, pipeline: bool = False, multi_step: int = 1):
        self.params = params
        self.cfg = cfg
        self.eos_id = eos_id
        self.page = page
        self.chunk = page if chunk is None else chunk
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        self.max_queue = max_queue
        self.admission = admission
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        # optional write-ahead TokenJournal (serving/checkpoint.py): token
        # appends / done / reset records per tick, fsynced once per step()
        # BEFORE results are returned — crash recovery resumes from here
        self.journal = journal
        # pipeline: defer each tick's sampling readback one step so host
        # scheduling for tick N+1 overlaps device execution of tick N;
        # multi_step additionally fuses up to K pure-decode ticks into one
        # jitted lax.scan launch when no admission/retire event can land
        # inside the window.  Token-exact vs the synchronous engine by
        # construction (docs/serving.md "Pipelined engine"); with a draft
        # model attached the speculative-decoding scheduler policy stays
        # on the synchronous path (its rounds are already fused).
        self.pipeline = bool(pipeline)
        self.multi_step = int(multi_step)
        if self.multi_step < 1:
            raise ValueError(f"multi_step must be >= 1, got {multi_step}")
        if self.multi_step > 1 and not self.pipeline:
            raise ValueError("multi_step > 1 requires pipeline=True")
        self._pending: Optional[_Pending] = None
        self._flushed_done: List[Tuple[int, List[int]]] = []
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        # quantize: False keeps the pool at cfg.dtype; True/"int8" or "fp8"
        # makes that 1 B/elem dtype the pool's NATIVE storage (per-page
        # scale banks ride beside the pages; resolve_pool_dtype validates)
        self.state, self.pool = init_paged_state(
            cfg, slots=slots, n_pages=n_pages, page=page,
            max_pages_per_seq=max_pages_per_seq, quantize=quantize)
        self.quantize = quantize
        # obs label + per-page HBM cost for serve.page_pool_bytes: the
        # pool's storage dtype tag ("int8"/"fp8", else the full-precision
        # jnp dtype name) and bytes per held page across k/v/scale banks
        self._pool_dtype = (self.pool.dtype or
                            jnp.dtype(self.state.k_pages[0].dtype).name)
        banks = list(self.state.k_pages) + list(self.state.v_pages)
        if self.state.k_scales is not None:
            banks += list(self.state.k_scales) + list(self.state.v_scales)
        self._page_nbytes = sum(a.nbytes // a.shape[0] for a in banks)
        # None: probe per launch width; True/False force a path
        self.use_ragged = use_ragged
        self._attn_cache: Dict[int, str] = {}
        # content-hashed prefix cache (models/paged_decode.PrefixCache):
        # admission pins cached pages by refcount and skips their prefill;
        # every write to a shared page goes through the CoW barrier
        self.cache = PrefixCache(self.pool) if prefix_cache else None
        # group_attn: score each prefix group's shared pages once per tick
        # (attn="grouped") when >= 2 live members share pinned pages;
        # False keeps the plain per-slot launch (still prefill-skipping)
        self.group_attn = group_attn
        # slot -> the tuple of shared page ids pinned at admission; the
        # grouping key for attn="grouped".  Trimmed when the CoW barrier
        # privatizes a boundary page, dropped at retire/drain.
        self._shared: Dict[int, Tuple[int, ...]] = {}
        self.draft = None
        self.spec_k = 0
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if temperature != 0.0:
                raise ValueError("speculative serving requires "
                                 "temperature == 0")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft and target must share a vocabulary")
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self.draft = (draft_params, draft_cfg)
            self.spec_k = spec_k
            self.dstate, self.dpool = init_paged_state(
                draft_cfg, slots=slots, n_pages=n_pages, page=page,
                max_pages_per_seq=max_pages_per_seq, quantize=quantize)
        self.slots: List[Optional[_Request]] = [None] * slots
        self._next_tok = np.zeros((slots,), np.int32)
        self._queue: List[_Request] = []
        self._next_id = 0
        self._finished: Dict[int, List[int]] = {}
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rounds = 0

    # -- client surface ----------------------------------------------------

    def _reject(self, exc_cls, reason: RejectReason, message: str):
        _M_REJECTED.inc(reason=reason.value)
        raise exc_cls(reason, message)

    def _occupancy(self) -> float:
        """Live PHYSICAL pool occupancy, the same value
        `serve.page_pool_occupancy` exports (fraction of usable pages
        held; a shared page counts once; page 0 is the sink)."""
        usable = self.pool.n_pages - 1
        return (usable - self.pool.available) / usable if usable else 0.0

    def _set_pool_gauges(self) -> None:
        """Physical occupancy (each shared page ONCE — what actually
        bounds admission) on both the legacy gauge and its explicit
        `_physical` alias, plus the logical view (sum of refcounts; the
        gap is pages saved by sharing)."""
        occ = self._occupancy()
        _M_POOL.set(occ)
        _M_POOL.set(occ, dtype=self._pool_dtype)
        _M_POOL_PHYS.set(occ)
        usable = self.pool.n_pages - 1
        _M_POOL_LOG.set(self.pool.logical_refs / usable if usable else 0.0)
        held = usable - self.pool.available if usable else 0
        _M_POOL_BYTES.set(held * self._page_nbytes, dtype=self._pool_dtype)

    def submit(self, tokens, max_new_tokens: int) -> int:
        """Queue a prompt; returns a request id.  Raises InvalidRequest
        (a ValueError) on malformed / permanently unservable requests,
        LoadShed (a RuntimeError) when shed — both carry a typed
        `.reason` matching the `rejected{reason=…}` counter label.  Pool
        pressure sheds BEFORE queue pressure, hard exhaustion before the
        soft `admission` policy."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size == 0:
            self._reject(InvalidRequest, RejectReason.EMPTY_PROMPT,
                         "empty prompt")
        if max_new_tokens < 1:
            self._reject(InvalidRequest, RejectReason.BAD_BUDGET,
                         f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens}")
        need = self._pages_for(tokens.size, max_new_tokens)
        if need > self.state.page_table.shape[1]:
            self._reject(InvalidRequest, RejectReason.TABLE_WIDTH,
                         f"request needs {need} pages > max_pages_per_seq "
                         f"{self.state.page_table.shape[1]}")
        if need > self.pool.n_pages - 1:  # page 0 is the reserved sink
            self._reject(InvalidRequest, RejectReason.POOL_SIZE,
                         f"request needs {need} pages but the pool only has "
                         f"{self.pool.n_pages - 1} usable pages total")
        if self.max_queue is not None:
            # pool pressure first: a request that would queue behind others
            # for pages that are not free only deepens the backlog; pages
            # the prefix cache could evict on demand count as free here
            avail = self.pool.available
            if self.cache is not None:
                avail += self.cache.evictable()
            if self._queue and need > avail:
                self._reject(LoadShed, RejectReason.POOL_EXHAUSTED,
                             f"load shed (pool-exhausted): request needs "
                             f"{need} pages, {avail} free or evictable, "
                             f"{len(self._queue)} already waiting")
            if len(self._queue) >= self.max_queue:
                self._reject(LoadShed, RejectReason.QUEUE_FULL,
                             f"load shed (queue-full): {len(self._queue)} "
                             f"waiting >= max_queue {self.max_queue}")
        if self.admission is not None:
            occ = self._occupancy()
            reason = self.admission.decide(queue_depth=len(self._queue),
                                           pool_occupancy=occ)
            if reason is not None:
                self._reject(LoadShed, reason,
                             f"load shed ({reason}): admission policy — "
                             f"queue_depth={len(self._queue)}, "
                             f"pool_occupancy={occ:.3f}")
        rid = self._next_id
        self._next_id += 1
        req = _Request(rid, tokens, max_new_tokens,
                       t_submit=time.perf_counter())
        # attribute, not a dataclass field — checkpoint serialization must
        # not see the trace context (same contract as _prefix_hashes)
        req._tc = tracing.start_request(rid)
        self._queue.append(req)
        _M_SUBMITTED.inc()
        _M_QUEUE.set(len(self._queue))
        return rid

    def try_submit(self, tokens, max_new_tokens: int) -> SubmitResult:
        """Non-raising submit for routers: rid on success, typed reason
        (with its `retryable` bit) on rejection."""
        try:
            return SubmitResult(rid=self.submit(tokens, max_new_tokens))
        except SubmitRejected as e:
            return SubmitResult(reason=e.reason, message=str(e))

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def live(self) -> int:
        return sum(r is not None for r in self.slots)

    def results(self) -> Dict[int, List[int]]:
        return dict(self._finished)

    @property
    def acceptance_rate(self) -> Optional[float]:
        if self.spec_proposed == 0:
            return None
        return self.spec_accepted / self.spec_proposed

    def run(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        with obs.span("serve.run"):
            for _ in range(max_steps):
                if not self._queue and self.live == 0:
                    return self.results()
                self.step()
        raise RuntimeError(f"run() exceeded {max_steps} steps")

    def drain(self) -> List[int]:
        """Graceful shutdown: release every in-flight slot's pages and put
        its request BACK at the queue head (reset to un-prefilled; greedy
        decode regenerates the identical tokens on re-admission), then
        refresh the gauges so a drained engine reads live=0 /
        occupancy=0.  Returns the requeued rids in their new queue order.
        The engine stays usable — run() after drain() serves everything,
        requeued work first, to completion."""
        # quiesce the pipeline first: an in-flight launch's tokens are
        # accounted (and its finishers retired through the journal) before
        # the survivors are reset and requeued
        self.flush_pipeline()
        inflight = [req for req in self.slots if req is not None]
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            self.state = free_slot(self.state, self.pool, slot)
            if self.draft is not None:
                self.dstate = retire_slot(self.dstate, self.dpool, slot)
            self.slots[slot] = None
        self._shared.clear()
        inflight.sort(key=lambda r: r.rid)
        for req in reversed(inflight):
            req.tokens = []
            req.n_prefilled = 0
            self._queue.insert(0, req)
            if self.journal is not None:
                self.journal.reset(req.rid)
        if self.journal is not None:
            self.journal.sync()
        _M_QUEUE.set(len(self._queue))
        _M_LIVE.set(0)
        self._set_pool_gauges()
        return [r.rid for r in inflight]

    # -- engine ------------------------------------------------------------

    def _pages_for(self, prompt_len: int, max_new: int) -> int:
        slack = self.spec_k + 1 if self.draft is not None else 0
        return -(-(prompt_len + max_new + slack) // self.page)

    def _attn_for(self, qt: int) -> str:
        """Kernel route for a launch width, probed once per width; a
        declined probe counts one labeled fallback per width."""
        if self.use_ragged is True:
            return "ragged"
        if self.use_ragged is False:
            return "dense"
        if qt not in self._attn_cache:
            reason = ragged_supported(
                n_kv_heads=self.cfg.n_kv_heads, n_q_heads=self.cfg.n_heads,
                q_tokens=qt, d_head=self.cfg.d_head, page=self.page,
                quantized=self.quantize)
            if reason is not None:
                _M_FALLBACK.inc(reason=_fallback_label(reason),
                                **{"pass": "serve"})
                logger.info("ragged kernel declined (qt=%d): %s — dense "
                            "fallback", qt, reason)
            self._attn_cache[qt] = "dense" if reason is not None else "ragged"
        return self._attn_cache[qt]

    def _hashes(self, req: _Request) -> List[bytes]:
        """Full-page rolling hash chain of `req.prompt`, memoized on the
        request (an attribute, not a dataclass field — checkpoint
        serialization must not see it)."""
        h = getattr(req, "_prefix_hashes", None)
        if h is None:
            h = PrefixCache.chain(req.prompt, self.page,
                                  dtype=self.pool.dtype)
            req._prefix_hashes = h
        return h

    def _register_prefix(self, slot: int, req: _Request,
                         row: Optional[np.ndarray] = None) -> None:
        """Register a just-prefilled prompt's full pages in the prefix
        cache.  Runs AFTER the prompt-completing chunk, so any CoW the
        re-absorbed last token forced has already rewritten the table —
        the registered page ids are the post-CoW (content-correct) ones;
        insert() is touch-only for hashes already cached.  The pipelined
        engine registers at deferred-readback time and passes the table
        `row` it captured at launch, so a later speculative launch's CoW
        can never shift the registered ids (and reading the row never
        forces a device sync on an in-flight state)."""
        if self.cache is None:
            return
        hashes = self._hashes(req)
        if not hashes:
            return
        if row is None:
            row = np.asarray(self.state.page_table[slot])
        row = row[:len(hashes)]
        self.cache.insert(hashes, [int(p) for p in row])

    def _admit(self) -> None:
        """Reserve queued requests' full page lifetime into free slots
        (FIFO; the head is never starved by admitting behind it).  No
        tokens move here — prefill is chunked through subsequent ticks.

        With a prefix cache, the head's prompt is first looked up in the
        hash chain: hit pages are pinned (refcount bumped) and wired into
        the slot's table directly, chunked prefill resumes at the
        divergence point, and only the remainder is acquired fresh.  A
        FULL-prompt hit resumes at T-1 so the last prompt token is
        re-absorbed through one ragged chunk — that re-scatter into the
        last shared page is what the CoW barrier privatizes."""
        for slot, occupant in enumerate(self.slots):
            if occupant is not None or not self._queue:
                continue
            req = self._queue[0]
            need = self._pages_for(len(req.prompt), req.max_new_tokens)
            hits: List[int] = []
            if self.cache is not None:
                hits = self.cache.lookup(self._hashes(req))
                short = (need - len(hits)) - self.pool.available
                if short > 0:
                    self.cache.evict(short)
                need -= len(hits)
            if need > self.pool.available:
                if hits:
                    self.pool.release(hits)
                break
            if self.draft is not None and \
                    need + len(hits) > self.dpool.available:
                if hits:
                    self.pool.release(hits)
                break
            ids = self.pool.acquire(need)
            try:
                self.state = assign_pages(self.state, slot, hits + ids)
                if hits:
                    t_pre = len(hits) * self.page
                    # full-prompt hit: resume at T-1, not T — the engine
                    # needs the last token's logits to sample token 0, so
                    # one token is re-absorbed through a 1-token chunk
                    t_resume = (t_pre if t_pre < len(req.prompt)
                                else len(req.prompt) - 1)
                    self.state = self.state._replace(
                        lengths=self.state.lengths.at[slot].set(t_resume))
                    req.n_prefilled = t_resume
                    self._shared[slot] = tuple(hits)
                    _M_PREFIX_HITS.inc()
                    _M_PAGES_SHARED.inc(len(hits))
                    _M_SKIPPED.inc(t_resume)
                elif self.cache is not None:
                    _M_PREFIX_MISSES.inc()
                if self.draft is not None:
                    # draft prefills its WHOLE prompt now (one program, the
                    # draft is cheap); its cache then tracks the target's
                    # accepted stream via per-tick catch-up steps
                    dp, dc = self.draft
                    _, self.dstate = paged_prefill(
                        dp, jnp.asarray(req.prompt), self.dstate,
                        self.dpool, slot, dc)
                    self.dstate = provision_capacity(
                        self.dstate, self.dpool, slot,
                        req.max_new_tokens + self.spec_k + 1)
            except Exception:
                # free_slot releases hits and ids together (one ref each —
                # the lookup's pin and the acquire both belong to the row)
                req.n_prefilled = 0
                self._shared.pop(slot, None)
                self.state = free_slot(self.state, self.pool, slot)
                if self.draft is not None:
                    try:
                        self.dstate = retire_slot(self.dstate, self.dpool,
                                                  slot)
                    except Exception as rollback_err:  # noqa: BLE001
                        logger.warning(
                            "admission rollback: draft retire_slot(%d) "
                            "failed (%s: %s); continuing", slot,
                            type(rollback_err).__name__, rollback_err)
                raise
            self._queue.pop(0)
            self.slots[slot] = req
            _M_ADMITTED.inc()
            _M_QUEUE.set(len(self._queue))
            tc = getattr(req, "_tc", None)
            if tc is not None:
                req._t_admit = time.perf_counter()
                tracing.record_span(tc, "serve.queued", req.t_submit,
                                    req._t_admit)

    def _cow_barrier(self, q_lens) -> None:
        """Privatize every page the imminent launch will scatter into
        while the allocator holds it at refcount > 1 (serving/model.
        cow_pages), and trim the slot's pinned-prefix key past the first
        privatized column.  Gated on pool.has_shared so cache-off and
        zero-overlap runs never pay the scan."""
        if not self.pool.has_shared:
            return
        for slot, req in enumerate(self.slots):
            if req is None or not q_lens[slot]:
                continue
            self.state, copies = cow_pages(
                self.state, self.pool, slot, int(q_lens[slot]),
                cache=self.cache)
            if not copies:
                continue
            _M_COW.inc(len(copies))
            shared = self._shared.get(slot)
            if shared:
                first = min(col for col, _, _ in copies)
                if first < len(shared):
                    if first:
                        self._shared[slot] = shared[:first]
                    else:
                        del self._shared[slot]

    def _build_groups(self):
        """Group live slots whose pinned shared-prefix tuples are EXACTLY
        equal; returns (group_id[slots], shared_table[n_groups+1, n_sh],
        shared_lens[n_groups+1]) device arrays, or None unless some group
        has >= 2 live members (a 1-member "group" saves nothing and would
        only move its math off the bit-identical plain path).  Group 0 is
        the null group (shared_lens 0) every ungrouped slot rides in;
        n_sh is padded to a power of two to bound retraces."""
        groups: Dict[Tuple[int, ...], List[int]] = {}
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            key = self._shared.get(slot)
            if key:
                groups.setdefault(key, []).append(slot)
        real = sorted((k, v) for k, v in groups.items() if len(v) >= 2)
        if not real:
            return None
        n_sh = max(len(k) for k, _ in real)
        n_sh = 1 << (n_sh - 1).bit_length()
        gid = np.zeros((len(self.slots),), np.int32)
        # group axis padded to slots+1 rows (compile-stable: the traced
        # shape never varies with how many groups this tick happens to
        # have; at most slots//2 rows are real, the rest stay null)
        n_rows = len(self.slots) + 1
        table = np.zeros((n_rows, n_sh), np.int32)
        lens = np.zeros((n_rows,), np.int32)
        for g, (key, members) in enumerate(real, start=1):
            table[g, :len(key)] = key
            lens[g] = len(key) * self.page
            for s in members:
                gid[s] = g
        return jnp.asarray(gid), jnp.asarray(table), jnp.asarray(lens)

    def _sample(self, logits):
        self._rng, key = jax.random.split(self._rng)
        return np.asarray(sample_logits(
            logits, key, temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, nan_sentinel=True))

    def _retire_finished(self) -> List[Tuple[int, List[int]]]:
        done = []
        retiring: List[int] = []
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            hit_eos = self.eos_id is not None and req.tokens \
                and req.tokens[-1] == self.eos_id
            if hit_eos or len(req.tokens) >= req.max_new_tokens:
                retiring.append(slot)
                if self.draft is not None:
                    self.dstate = retire_slot(self.dstate, self.dpool, slot)
                self.slots[slot] = None
                self._shared.pop(slot, None)
                self._finished[req.rid] = req.tokens
                done.append((req.rid, req.tokens))
                if self.journal is not None:
                    self.journal.done(req.rid)
                _M_RETIRED.inc(cause="eos" if hit_eos else "budget")
                tc = getattr(req, "_tc", None)
                if tc is not None:
                    now = time.perf_counter()
                    tracing.record_span(
                        tc, "serve.decode",
                        getattr(req, "_t_first", req.t_submit), now,
                        tokens=len(req.tokens))
                    tracing.record_span(tc, "serve.request", req.t_submit,
                                        now, root=True, rid=req.rid)
        if retiring:
            # one batched table edit for the whole wave (pages release in
            # slot order, so the pool free list matches per-slot frees)
            self.state = free_slots(self.state, self.pool, retiring)
        if done:
            # retirement frees pages AFTER the tick's _note_tick ran; keep
            # the gauges honest so a drained engine reads occupancy 0
            _M_LIVE.set(self.live)
            self._set_pool_gauges()
        return done

    def _note_tick(self, dt: float, added: int,
                   dev_s: Optional[float] = None) -> None:
        # dev_s = the tick's device launch+sample window; the remainder is
        # host gap, folded into the cumulative serve.host_gap_fraction gauge
        if dev_s is not None:
            self._host_gap_s = getattr(self, "_host_gap_s", 0.0) \
                + max(0.0, dt - dev_s)
            self._launch_wall_s = getattr(self, "_launch_wall_s", 0.0) + dt
            _M_HOST_GAP.set(self._host_gap_s / self._launch_wall_s)
        _M_STEPS.inc()
        _M_QUEUE.set(len(self._queue))
        live = self.live
        _M_LIVE.set(live)
        self._set_pool_gauges()
        if added:
            _M_TOKENS.inc(added)
            _M_TOK_LAT.observe(dt * live / added)
        rate = self.acceptance_rate
        if rate is not None:
            _M_SPEC_RATE.set(rate)

    def _journal_barrier(self, done: List[Tuple[int, List[int]]]) -> None:
        """Durability-then-delivery barrier: fsync the tick's journal
        appends, then run the journal machine's deliver transition for
        every stream leaving the engine — protocols.journal raises if any
        returned token is not yet durable (the delivered ⟹ durable
        contract burstcheck model-checks as proto-journal-durable)."""
        if self.journal is None:
            return
        self.journal.sync()
        for rid, toks in done:
            self.journal.delivered(rid, len(toks))

    def step(self) -> List[Tuple[int, List[int]]]:
        """One engine tick (see _step; _pipelined_step when pipeline=True
        and no draft model is attached).  When a journal is attached this
        is also the durability barrier: the tick's journal appends are
        fsynced BEFORE its results are returned, so any token a caller
        has seen survives a crash (write-ahead).  On the pipelined path
        the fsync stays before delivery — which means delivery lags one
        step behind generation (the launch whose tokens are returned here
        was dispatched a step ago; this tick's launch is still in
        flight)."""
        if self.pipeline and self.draft is None:
            return self._pipelined_step()
        done = self._step()
        self._journal_barrier(done)
        return done

    def _step(self) -> List[Tuple[int, List[int]]]:
        """One engine tick: retire -> admit -> ONE ragged launch moving
        every active slot (prefill chunks + decode singles together, or a
        whole speculative round when a draft is attached and nothing is
        mid-prefill).  Returns requests that finished THIS tick."""
        t0 = time.perf_counter()
        done = self._retire_finished()
        self._admit()
        if self.live == 0:
            self._note_tick(time.perf_counter() - t0, 0)
            return done

        prefilling = [s for s, r in enumerate(self.slots)
                      if r is not None and r.n_prefilled < len(r.prompt)]
        if self.draft is not None and not prefilling:
            td0 = time.perf_counter()
            added = self._spec_round()
            # the whole round counts as device window (its launches are
            # back-to-back; the python glue between them is noise here)
            self._note_tick(time.perf_counter() - t0, added,
                            time.perf_counter() - td0)
            done += self._retire_finished()
            return done

        qt = self.chunk if prefilling else 1
        slots = len(self.slots)
        toks = np.zeros((slots, qt), np.int32)
        q_lens = np.zeros((slots,), np.int32)
        n_prefill_toks = 0
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if req.n_prefilled < len(req.prompt):
                seg = req.prompt[req.n_prefilled:req.n_prefilled + qt]
                toks[slot, :len(seg)] = seg
                q_lens[slot] = len(seg)
                n_prefill_toks += len(seg)
            else:
                toks[slot, 0] = self._next_tok[slot]
                q_lens[slot] = 1
        self._cow_barrier(q_lens)
        td0 = time.perf_counter()  # device window: launch through sample sync
        attn = self._attn_for(qt)
        groups = (self._build_groups()
                  if self.group_attn and self._shared and attn == "ragged"
                  else None)
        if groups is not None:
            gid, gtable, glens = groups
            logits, self.state = ragged_model_step(
                self.params, jnp.asarray(toks), jnp.asarray(q_lens),
                self.state, self.cfg, attn="grouped", group_id=gid,
                shared_table=gtable, shared_lens=glens)
        else:
            logits, self.state = ragged_model_step(
                self.params, jnp.asarray(toks), jnp.asarray(q_lens),
                self.state, self.cfg, attn=attn)
        choice = self._sample(logits)
        dev_s = time.perf_counter() - td0

        kind = ("mixed" if prefilling and len(prefilling) < self.live
                else "prefill" if prefilling else "decode")
        _M_RB_LAUNCH.inc(kind=kind)
        if n_prefill_toks:
            _M_RB_PREFILL.inc(n_prefill_toks)
        _M_RB_FILL.set(float(q_lens.sum()) / (slots * qt))

        added = 0
        dtoks = np.zeros((slots,), np.int32)   # draft catch-up feed
        dlens = np.zeros((slots,), np.int32)
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if choice[slot] < 0:  # sample_logits NaN-poison sentinel
                raise RuntimeError(
                    f"slot {slot} (rid {req.rid}) logits are NaN-poisoned: "
                    "a live slot was stepped without assigned pages")
            if req.n_prefilled < len(req.prompt):
                was = req.n_prefilled
                req.n_prefilled = was + int(q_lens[slot])
                if req.n_prefilled == len(req.prompt):
                    self._register_prefix(slot, req)
                    # chunk completed the prompt: its last-token logits ARE
                    # the first-token distribution (TTFT lands here)
                    tok = int(choice[slot])
                    req.tokens.append(tok)
                    if self.journal is not None:
                        self.journal.tokens(req.rid, [tok])
                    self._next_tok[slot] = tok
                    added += 1
                    now = time.perf_counter()
                    _M_TTFT.observe(now - req.t_submit)
                    tc = getattr(req, "_tc", None)
                    if tc is not None:
                        # contiguous phases on one clock: queued ends where
                        # prefill starts, prefill ends at the first-token
                        # instant — the breakdown sums to TTFT exactly
                        t_adm = getattr(req, "_t_admit", req.t_submit)
                        req._t_first = now
                        tracing.record_span(tc, "serve.prefill", t_adm, now,
                                            prompt_len=len(req.prompt))
                        tracing.marker(tc, "serve.first_token", now)
                        tracing.note_ttft(tc, now - req.t_submit)
                        tracing.publish_breakdown(
                            {"queued": t_adm - req.t_submit,
                             "prefill": now - t_adm})
            else:
                tok = int(choice[slot])
                req.tokens.append(tok)
                if self.journal is not None:
                    self.journal.tokens(req.rid, [tok])
                # draft cache catch-up: it must absorb the token the target
                # just consumed (the PREVIOUS next_tok) to stay aligned
                dtoks[slot] = toks[slot, 0]
                dlens[slot] = 1
                self._next_tok[slot] = tok
                added += 1
                _M_RB_DECODE.inc()
        if self.draft is not None and dlens.any():
            dp, dc = self.draft
            _, self.dstate = ragged_model_step(
                dp, jnp.asarray(dtoks[:, None]), jnp.asarray(dlens),
                self.dstate, dc, attn="dense")
        self._note_tick(time.perf_counter() - t0, added, dev_s)
        done += self._retire_finished()
        return done

    # -- pipelined engine --------------------------------------------------
    #
    # step() under pipeline=True keeps exactly one launch in flight: each
    # tick dispatches the NEXT launch (speculatively, when no admission or
    # retire event can land at the unread launch's readback) BEFORE
    # blocking on the previous one, so host scheduling for tick N+1
    # overlaps device execution of tick N.  The readback replays the
    # synchronous engine's post-sample accounting one step late; the
    # journal fsync stays before delivery, so delivery lags one step.
    # Token-exactness rests on two facts: (1) every launch is the SAME
    # compiled program as the synchronous tick (burstlint asserts the K=1
    # jaxprs are string-identical), and (2) jax.random.categorical's
    # per-row noise depends only on (key, shape, row) — a slot's sampled
    # token never depends on other slots' logits — so feeding a still-on-
    # device choice into the next launch cannot change any slot's stream.

    def _spec_plan(self) -> Optional[int]:
        """Fused decode depth k for a speculative launch on top of the
        unread pending launch, or None when the synchronous engine could
        admit or retire at the pending readback (speculating would build
        on a wrong schedule; EOS is the one event this cannot predict —
        the reconcile path in _pipelined_step handles it)."""
        p = self._pending
        if self._queue and any(r is None for r in self.slots):
            return None                  # admission would land next tick
        any_live = False
        k = self.multi_step
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            any_live = True
            if req.n_prefilled + int(p.prefill_advance[slot]) \
                    < len(req.prompt):
                return None              # still mid-prefill after pending
            remaining = req.max_new_tokens \
                - (len(req.tokens) + int(p.tok_delta[slot]))
            if remaining < 1:
                return None              # budget retire at pending readback
            k = min(k, remaining)
        if not any_live:
            return None
        if self._shared and self.group_attn:
            # shared-prefix ticks follow the synchronous engine's per-tick
            # grouped-launch decision; never fuse across them
            k = 1
        return k

    def _dispatch_deferred(self, *, feed, q_lens, qt, k, prefill_advance,
                           tok_delta, n_prefill_toks, kind) -> _Pending:
        """Shared dispatch for both pipelined launch flavors: CoW-protect
        the window, route the kernel, launch WITHOUT reading the sampled
        choice back.  `feed` is the [slots, qt] token grid for k == 1 or
        the [slots] next-token feed for a fused k-step scan (either host
        numpy or a still-in-flight device array)."""
        self._cow_barrier(q_lens * k)
        # capture the post-CoW table row of any slot completing its prompt
        # this launch: prefix registration at readback must see the table
        # exactly as the synchronous engine would, before a later launch's
        # CoW rewrites it
        table_rows: Dict[int, np.ndarray] = {}
        if self.cache is not None:
            for slot, req in enumerate(self.slots):
                if req is not None and prefill_advance[slot] and \
                        req.n_prefilled + int(prefill_advance[slot]) \
                        == len(req.prompt):
                    table_rows[slot] = np.asarray(self.state.page_table[slot])
        attn = self._attn_for(qt)
        rng_before = self._rng
        q_lens_dev = jnp.asarray(q_lens)
        t_d = time.perf_counter()
        if k > 1:
            choices, self.state, self._rng = multi_step_decode(
                self.params, jnp.asarray(feed), q_lens_dev, self.state,
                self._rng, self.cfg, k=k, attn=attn,
                temperature=self.temperature, top_k=self.top_k,
                top_p=self.top_p)
            _M_MULTI.inc(k=str(k))
        else:
            groups = (self._build_groups()
                      if self.group_attn and self._shared
                      and attn == "ragged" else None)
            self._rng, key = jax.random.split(self._rng)
            if groups is not None:
                gid, gtable, glens = groups
                choice, self.state = pipelined_tick(
                    self.params, jnp.asarray(feed), q_lens_dev, self.state,
                    key, self.cfg, attn="grouped",
                    temperature=self.temperature, top_k=self.top_k,
                    top_p=self.top_p, group_id=gid, shared_table=gtable,
                    shared_lens=glens)
            else:
                choice, self.state = pipelined_tick(
                    self.params, jnp.asarray(feed), q_lens_dev, self.state,
                    key, self.cfg, attn=attn,
                    temperature=self.temperature, top_k=self.top_k,
                    top_p=self.top_p)
            choices = choice[None]
        _M_RB_LAUNCH.inc(kind=kind)
        if n_prefill_toks:
            _M_RB_PREFILL.inc(n_prefill_toks)
        _M_RB_FILL.set(float(q_lens.sum()) / (len(self.slots) * qt))
        return _Pending(
            choices=choices, k=k, q_lens=q_lens,
            advance=(q_lens * k).astype(np.int32),
            prefill_advance=prefill_advance, tok_delta=tok_delta,
            rng_before=rng_before, table_rows=table_rows,
            n_prefill_toks=n_prefill_toks, kind=kind, t_dispatch=t_d,
            feed_next=choices[-1])

    def _launch_deferred(self) -> _Pending:
        """Pipeline (re)fill: the synchronous tick's batch build — prefill
        chunks + decode singles from the fully-accounted host state — as
        one deferred launch, fused to multi_step depth when every live
        slot is pure-decode and no admission/retire can land inside the
        window."""
        prefilling = [s for s, r in enumerate(self.slots)
                      if r is not None and r.n_prefilled < len(r.prompt)]
        qt = self.chunk if prefilling else 1
        slots = len(self.slots)
        toks = np.zeros((slots, qt), np.int32)
        q_lens = np.zeros((slots,), np.int32)
        prefill_advance = np.zeros((slots,), np.int32)
        tok_delta = np.zeros((slots,), np.int32)
        n_prefill_toks = 0
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if req.n_prefilled < len(req.prompt):
                seg = req.prompt[req.n_prefilled:req.n_prefilled + qt]
                toks[slot, :len(seg)] = seg
                q_lens[slot] = len(seg)
                prefill_advance[slot] = len(seg)
                if req.n_prefilled + len(seg) == len(req.prompt):
                    tok_delta[slot] = 1
                n_prefill_toks += len(seg)
            else:
                toks[slot, 0] = self._next_tok[slot]
                q_lens[slot] = 1
                tok_delta[slot] = 1
        k = 1
        if not prefilling and self.multi_step > 1 \
                and not (self._shared and self.group_attn) \
                and not (self._queue
                         and any(r is None for r in self.slots)):
            k = self.multi_step
            for req in self.slots:
                if req is not None:
                    k = min(k, req.max_new_tokens - len(req.tokens))
            k = max(1, k)
        if k > 1:
            tok_delta = q_lens * k
        kind = ("mixed" if prefilling and len(prefilling) < self.live
                else "prefill" if prefilling else "decode")
        return self._dispatch_deferred(
            feed=(toks if k == 1 else toks[:, 0]), q_lens=q_lens, qt=qt,
            k=k, prefill_advance=prefill_advance, tok_delta=tok_delta,
            n_prefill_toks=n_prefill_toks, kind=kind)

    def _launch_speculative(self, k: int) -> _Pending:
        """Launch the next k decode steps on top of the UNREAD pending
        launch, feeding its last on-device choice row straight in as the
        next tokens — zero host readbacks between the two launches."""
        p = self._pending
        slots = len(self.slots)
        q_lens = np.asarray([1 if r is not None else 0
                             for r in self.slots], np.int32)
        feed = p.feed_next if p.feed_next is not None else p.choices[-1]
        return self._dispatch_deferred(
            feed=(feed[:, None] if k == 1 else feed), q_lens=q_lens, qt=1,
            k=k, prefill_advance=np.zeros((slots,), np.int32),
            tok_delta=q_lens * k, n_prefill_toks=0, kind="decode")

    def _readback(self, p: _Pending) -> Tuple[int, bool, bool]:
        """Deferred host half of launch `p`: block on its sampled choices
        (THE pipeline sync point) and replay the synchronous engine's
        post-sample accounting.  A fused launch is truncated at its FIRST
        EOS step — tokens past it are schedule the synchronous engine
        would never have produced — by rolling the device lengths back
        and re-deriving the rng from the pre-launch snapshot, so the
        per-slot streams stay bit-identical.  Returns (tokens added,
        diverged, truncated); `diverged` means the readback produced an
        event (EOS / budget retire / truncation) that invalidates any
        schedule speculated on top of this launch."""
        choices = _readback_choices(p.choices)
        slots = len(self.slots)
        keep = p.k
        if p.k > 1 and self.eos_id is not None:
            for j in range(p.k):
                if any(self.slots[s] is not None and p.q_lens[s]
                       and choices[j, s] == self.eos_id
                       for s in range(slots)):
                    keep = j + 1
                    break
        added = 0
        nan_at = None
        for j in range(keep):
            row = choices[j]
            for slot, req in enumerate(self.slots):
                if req is None or not p.q_lens[slot]:
                    continue
                if row[slot] < 0:  # sample_logits NaN-poison sentinel
                    nan_at = (slot, req.rid)
                    break
                if j == 0 and p.prefill_advance[slot]:
                    was = req.n_prefilled
                    req.n_prefilled = was + int(p.prefill_advance[slot])
                    if req.n_prefilled == len(req.prompt):
                        self._register_prefix(slot, req,
                                              row=p.table_rows.get(slot))
                        tok = int(row[slot])
                        req.tokens.append(tok)
                        if self.journal is not None:
                            self.journal.tokens(req.rid, [tok])
                        self._next_tok[slot] = tok
                        added += 1
                        now = time.perf_counter()
                        _M_TTFT.observe(now - req.t_submit)
                        tc = getattr(req, "_tc", None)
                        if tc is not None:
                            t_adm = getattr(req, "_t_admit", req.t_submit)
                            req._t_first = now
                            tracing.record_span(tc, "serve.prefill", t_adm,
                                                now,
                                                prompt_len=len(req.prompt))
                            tracing.marker(tc, "serve.first_token", now)
                            tracing.note_ttft(tc, now - req.t_submit)
                            tracing.publish_breakdown(
                                {"queued": t_adm - req.t_submit,
                                 "prefill": now - t_adm})
                else:
                    tok = int(row[slot])
                    req.tokens.append(tok)
                    if self.journal is not None:
                        self.journal.tokens(req.rid, [tok])
                    self._next_tok[slot] = tok
                    added += 1
                    _M_RB_DECODE.inc()
            if nan_at is not None:
                break
        truncated = keep < p.k
        if truncated:
            # scattered K/V beyond the rolled-back logical length is
            # harmless garbage — always overwritten before it can be read
            undo = np.where(p.q_lens > 0, p.k - keep, 0).astype(np.int32)
            self.state = self.state._replace(
                lengths=self.state.lengths - jnp.asarray(undo))
            rng = p.rng_before
            for _ in range(keep):
                rng, _ = jax.random.split(rng)
            self._rng = rng
            _M_RECONCILE.inc(cause="scan-eos")
        if nan_at is not None:
            slot, rid = nan_at
            raise RuntimeError(
                f"slot {slot} (rid {rid}) logits are NaN-poisoned: a live "
                "slot was stepped without assigned pages")
        eos = self.eos_id is not None and any(
            req is not None and req.tokens
            and req.tokens[-1] == self.eos_id for req in self.slots)
        budget = any(
            req is not None and len(req.tokens) >= req.max_new_tokens
            for req in self.slots)
        return added, (eos or budget or truncated), truncated

    def _pipelined_step(self) -> List[Tuple[int, List[int]]]:
        """One pipelined tick: dispatch the next launch (speculatively if
        safe), THEN block on the previous one — its results are what this
        call returns, so delivery lags one step.  On divergence (the
        readback retired a stream the speculation assumed live) the
        speculative launch is rolled back — lengths and rng restored —
        and the tick falls back to the synchronous retire/admit/launch
        sequence, so the schedule is always the synchronous engine's."""
        t0 = time.perf_counter()
        done = self._flushed_done
        self._flushed_done = []
        p = self._pending
        if p is None:
            # pipeline (re)fill: the synchronous tick head, one deferred
            # launch, nothing to read back or deliver yet
            done += self._retire_finished()
            self._admit()
            if self.live == 0:
                self._note_tick(time.perf_counter() - t0, 0)
                self._journal_barrier(done)
                return done
            self._pending = self._launch_deferred()
            dt = time.perf_counter() - t0
            self._note_tick(
                dt, 0, min(dt, time.perf_counter()
                           - self._pending.t_dispatch))
            self._journal_barrier(done)
            return done
        ir = getattr(p.choices, "is_ready", None)
        ready0 = bool(ir()) if ir is not None else False
        k_spec = self._spec_plan()
        spec = self._launch_speculative(k_spec) if k_spec else None
        self._pending = None
        added, diverged, truncated = self._readback(p)
        t_rb = time.perf_counter()
        if spec is not None and diverged:
            # reconcile: discard the speculative launch (its scattered K/V
            # sits beyond the logical length and is overwritten before it
            # can ever be read) and fall back to a synchronous tick
            self.state = self.state._replace(
                lengths=self.state.lengths - jnp.asarray(spec.advance))
            if not truncated:   # truncation already repositioned the rng
                self._rng = spec.rng_before
            _M_RECONCILE.inc(cause="eos-retire")
            spec = None
        if spec is not None:
            # speculation was right: the launch in flight IS the next tick
            self._pending = spec
        else:
            done += self._retire_finished()
            self._admit()
            if self.live:
                self._pending = self._launch_deferred()
        dt = time.perf_counter() - t0
        # device window estimate: the pending launch provably ran from
        # tick start to readback completion unless it was already ready
        # when the tick began; the freshly dispatched launch runs from
        # its dispatch to tick end (credited here, verified by the next
        # tick's is_ready probe)
        dev_s = 0.0 if ready0 else t_rb - t0
        if self._pending is not None:
            dev_s += time.perf_counter() - self._pending.t_dispatch
        self._note_tick(dt, added, min(dev_s, dt))
        self._journal_barrier(done)
        return done

    def flush_pipeline(self) -> List[Tuple[int, List[int]]]:
        """Quiesce the pipeline: block on any in-flight launch, run its
        deferred accounting, retire its finishers through the journal
        barrier.  The finishers are ALSO queued onto the next step()'s
        return so a driver loop polling step() never loses a completion.
        Safe no-op when nothing is in flight (or on a synchronous
        engine).  snapshot()/drain() call this first — a quiesced engine
        is the only thing worth serializing."""
        p = self._pending
        if p is None:
            return []
        self._pending = None
        added, _, _ = self._readback(p)
        done = self._retire_finished()
        if added:
            _M_TOKENS.inc(added)
        self._journal_barrier(done)
        self._flushed_done.extend(done)
        return done

    def _spec_round(self) -> int:
        """One speculative round for every (decoding) live slot: k draft
        proposals via single paged steps on the draft state, ONE ragged
        all-logits verify of [last | proposals] on the target, per-slot
        prefix acceptance, then a vectorized lengths rollback on both
        states.  Greedy; token-exact with the plain engine."""
        k = self.spec_k
        dp, dc = self.draft
        slots = len(self.slots)
        live_mask = np.asarray([r is not None for r in self.slots])
        # verify writes k+1 tokens per live slot into the TARGET state;
        # privatize any still-shared boundary page first (the draft pool
        # is never shared — draft prefill always acquires private pages)
        self._cow_barrier(np.where(live_mask, k + 1, 0))
        toks_dev = []
        cur = jnp.asarray(self._next_tok)
        bad_d = jnp.zeros(slots, bool)
        for _ in range(k):
            lg_d, self.dstate = paged_decode_step(dp, cur, self.dstate, dc)
            bad_d = bad_d | jnp.any(jnp.isnan(lg_d), axis=-1)
            cur = jnp.argmax(lg_d, axis=-1).astype(jnp.int32)
            toks_dev.append(cur)
        d_toks_dev = jnp.stack(toks_dev, axis=1)              # [slots, k]
        feed = jnp.concatenate(
            [jnp.asarray(self._next_tok)[:, None], d_toks_dev], axis=1)
        q_lens = jnp.asarray(np.where(live_mask, k + 1, 0).astype(np.int32))
        lg_t, self.state = ragged_model_step(
            self.params, feed, q_lens, self.state, self.cfg,
            attn=self._attn_for(k + 1), all_logits=True)
        # draft catch-up to base + k + 1, then the same rollback trims both
        _, self.dstate = paged_decode_step(
            dp, d_toks_dev[:, -1], self.dstate, dc)
        self.spec_rounds += 1
        _M_RB_LAUNCH.inc(kind="spec-verify")
        d_toks = np.asarray(d_toks_dev)
        choice = np.asarray(jnp.argmax(lg_t, axis=-1))        # [slots, k+1]
        bad = np.asarray(
            jnp.any(jnp.isnan(lg_t), axis=(1, 2)) | bad_d)
        undo = np.zeros(slots, np.int32)
        n_kept = 0
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if bad[slot]:
                raise RuntimeError(
                    f"slot {slot} (rid {req.rid}) speculative logits are "
                    "NaN-poisoned: stepped without provisioned capacity")
            n_acc = 0
            while n_acc < k and d_toks[slot, n_acc] == choice[slot, n_acc]:
                n_acc += 1
            self.spec_proposed += k
            self.spec_accepted += n_acc
            new = ([int(x) for x in d_toks[slot, :n_acc]]
                   + [int(choice[slot, n_acc])])
            new = new[: req.max_new_tokens - len(req.tokens)]
            if self.eos_id is not None and self.eos_id in new:
                new = new[: new.index(self.eos_id) + 1]
            req.tokens += new
            if self.journal is not None:
                self.journal.tokens(req.rid, new)
            n_kept += len(new)
            _M_RB_DECODE.inc(len(new))
            self._next_tok[slot] = new[-1]
            undo[slot] = k + 1 - len(new)
        undo_dev = jnp.asarray(undo)
        self.state = self.state._replace(
            lengths=self.state.lengths - undo_dev)
        self.dstate = self.dstate._replace(
            lengths=self.dstate.lengths - undo_dev)
        return n_kept
