"""The jitted model step behind RaggedServeEngine: scatter each slot's
new tokens' K/V into its pool pages, attend the whole ragged batch in one
kernel launch, return next-token logits.

One program serves EVERY engine tick shape with the same q-chunk width:
per-slot `q_lens` is traced (0 = idle slot, 1 = decode, up to the chunk
size = prefill), so admission/retirement/chunking never retrace.  The
compile key is (chunk width, attn path) — a continuous-batching engine
runs exactly two programs (chunk and 1) plus the speculative verify
width when a draft is attached.

`attn` selects the kernel: "ragged" is the one-launch Pallas kernel
(ops/ragged_paged.py); "dense" is the gather-based fallback the engine
routes through when `ragged_supported` declines the shape — same math,
paged_multi_step's dense-gather style, O(slots·max_ctx) memory.

Loud-failure contract (paged_decode.py's): a live slot whose tokens
would land in an unassigned (page 0) table column gets NaN logits — the
engine raises instead of silently attending sink-page garbage.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..models.decode import sample_logits
from ..models.paged_decode import (
    PagedState, PagePool, _gather_dequant_pages,
)
from ..models.transformer import (
    ModelConfig, _attn_out, _mlp, _qkv_proj, _rms_norm,
)
from ..ops.paged_attention import quantize_tokens
from ..ops.ragged_paged import (
    ragged_paged_attention, ragged_paged_attention_grouped,
)


def _dense_ragged_attention(q, kp, vp, ks, vs, table, pos, real,
                            cfg: ModelConfig):
    """Fallback path: dense-gather each slot's pages (including the just-
    scattered new tokens) and run masked softmax with the same per-row
    causal band the ragged kernel enforces.  q [S, Nq, QT, D]."""
    slots, n_q, qt, d = q.shape
    group = n_q // cfg.n_kv_heads
    kc = _gather_dequant_pages(kp, ks, table, cfg.n_kv_heads, cfg.d_head)
    vc = _gather_dequant_pages(vp, vs, table, cfg.n_kv_heads, cfg.d_head)
    qg = q.reshape(slots, cfg.n_kv_heads, group, qt, d)
    s = jnp.einsum("bngtd,bnjd->bngtj", qg.astype(jnp.float32),
                   kc.astype(jnp.float32)) * cfg.d_head**-0.5
    col = jnp.arange(kc.shape[2], dtype=jnp.int32)[None, None, :]
    visible = (col <= pos[:, :, None]) & real[:, :, None]
    if cfg.window is not None:
        visible &= col > pos[:, :, None] - cfg.window
    s = jnp.where(visible[:, None, None, :, :], s, float("-inf"))
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(visible[:, None, None, :, :], p, 0.0)  # masked rows -> 0
    o = jnp.einsum("bngtj,bnjd->bngtd", p, vc.astype(jnp.float32))
    return o.reshape(slots, n_q, qt, d).astype(q.dtype)


def _ragged_model_step(params, tokens, q_lens, state: PagedState,
                       cfg: ModelConfig, attn: str = "ragged",
                       all_logits: bool = False, group_id=None,
                       shared_table=None, shared_lens=None):
    """Advance every active slot by its own token count in ONE pass.

    tokens  [slots, QT] int32 — slot s consumes tokens[s, :q_lens[s]]
            (the rest is padding; idle slots pass q_lens == 0)
    q_lens  [slots] int32 (traced) — tokens this launch per slot
    state   donated PagedState; each slot's pages for positions
            lengths .. lengths+q_lens-1 must be pre-assigned
            (admission/provisioning — the engine's job)

    attn == "grouped" routes the shared-prefix grouped launch: the traced
    triple (group_id [slots], shared_table [G, n_sh], shared_lens [G])
    assigns each slot to a prefix group whose pinned pages are scored once
    and LSE-merged with the slot's private band (ops/ragged_paged.py).
    The engine only selects this path on ticks where some group has >= 2
    live members, so "ragged"/"dense" ticks stay bit-identical to today.

    Returns (logits, new state with lengths += q_lens):
      all_logits=False: [slots, vocab] fp32 at each slot's LAST consumed
        token — the next-token distribution a scheduler samples from.
      all_logits=True:  [slots, QT, vocab] fp32 (speculative verify).
    """
    if attn not in ("ragged", "dense", "grouped"):
        raise ValueError(
            f"attn must be 'ragged', 'dense' or 'grouped', got {attn!r}")
    if attn == "grouped" and (group_id is None or shared_table is None
                              or shared_lens is None):
        raise ValueError("attn='grouped' needs group_id, shared_table "
                         "and shared_lens")
    slots, qt = tokens.shape
    page = state.k_pages[0].shape[2]
    quant = state.k_scales is not None
    live = q_lens > 0
    base = jnp.where(live, state.lengths, 0)
    t_ix = jnp.arange(qt, dtype=jnp.int32)[None, :]
    real = (t_ix < q_lens[:, None]) & live[:, None]       # [slots, QT]
    pos = base[:, None] + t_ix                            # absolute positions
    slot_ix = jnp.arange(slots)[:, None]
    safe_col = jnp.minimum(pos // page, state.page_table.shape[1] - 1)
    pids = state.page_table[slot_ix, safe_col]
    # loud failure: a live slot's REAL token mapping to the sink page means
    # its page was never assigned — poison the logits (a jit cannot raise)
    boundary_unassigned = jnp.any(real & (pids == 0), axis=1)
    # padding/idle tokens scatter into the reserved sink page 0
    pids = jnp.where(real, pids, 0)
    offs = pos % page
    kv_lens = base + q_lens

    x = params["embed"].astype(cfg.dtype)[tokens]          # [slots, QT, dm]
    k_pools, v_pools, k_scs, v_scs = [], [], [], []
    for li, (p, kp, vp) in enumerate(zip(params["layers"], state.k_pages,
                                         state.v_pages)):
        q, k, v = _qkv_proj(p, x, pos, cfg)
        # scatter the new K/V FIRST so attention reads a complete pool
        k_rows = jnp.moveaxis(k, 1, 2)                     # [slots,QT,Nkv,D]
        v_rows = jnp.moveaxis(v, 1, 2)
        ks = vs = None
        if quant:
            k8, k_s = quantize_tokens(k_rows, dtype=kp.dtype)
            v8, v_s = quantize_tokens(v_rows, dtype=vp.dtype)
            kp = kp.at[pids, :, offs].set(k8)
            vp = vp.at[pids, :, offs].set(v8)
            ks = state.k_scales[li].at[pids, :, offs].set(k_s)
            vs = state.v_scales[li].at[pids, :, offs].set(v_s)
        else:
            kp = kp.at[pids, :, offs].set(k_rows.astype(kp.dtype))
            vp = vp.at[pids, :, offs].set(v_rows.astype(vp.dtype))
        if attn == "ragged":
            o = ragged_paged_attention(
                q, kp, vp, state.page_table, q_lens, kv_lens,
                k_scales=ks, v_scales=vs, window=cfg.window)
        elif attn == "grouped":
            o = ragged_paged_attention_grouped(
                q, kp, vp, state.page_table, q_lens, kv_lens,
                group_id=group_id, shared_table=shared_table,
                shared_lens=shared_lens,
                k_scales=ks, v_scales=vs, window=cfg.window)
        else:
            o = _dense_ragged_attention(q, kp, vp, ks, vs,
                                        state.page_table, pos, real, cfg)
        x = x + _attn_out(p, o)
        m, _ = _mlp(p, x, cfg, inference=True)
        x = x + m
        k_pools.append(kp)
        v_pools.append(vp)
        k_scs.append(ks)
        v_scs.append(vs)
    x = _rms_norm(x, params["final_norm"])
    if all_logits:
        logits = jnp.einsum("btd,vd->btv", x, params["lm_head"],
                            preferred_element_type=jnp.float32)
        logits = jnp.where(boundary_unassigned[:, None, None], jnp.nan,
                           logits)
    else:
        last = jnp.clip(q_lens - 1, 0, qt - 1)
        x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)
        logits = jnp.einsum("bsd,vd->bsv", x_last, params["lm_head"],
                            preferred_element_type=jnp.float32)[:, 0]
        logits = jnp.where(boundary_unassigned[:, None], jnp.nan, logits)
    lengths = state.lengths + jnp.where(live, q_lens, 0)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), state.page_table, lengths,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


ragged_model_step = partial(
    jax.jit, static_argnames=("cfg", "attn", "all_logits"),
    donate_argnums=(3,))(_ragged_model_step)


def pipelined_tick(params, tokens, q_lens, state: PagedState, key,
                   cfg: ModelConfig, *, attn: str = "ragged",
                   temperature: float = 0.0, top_k=None, top_p=None,
                   group_id=None, shared_table=None, shared_lens=None):
    """One engine tick with the sampled choice kept ON DEVICE.

    This is exactly the synchronous engine's tick — the same jitted
    ragged_model_step dispatch followed by the same sample_logits call —
    except the result is returned as a device array instead of being
    read back with np.asarray.  The pipelined engine feeds the choice
    straight into the next launch and defers the readback one step;
    burstlint asserts this function's jaxpr is string-identical to the
    synchronous composition, so pipelining can never change the compiled
    program, only when the host looks at its output.

    Returns (choice [slots] int32 device array, new PagedState)."""
    logits, state = ragged_model_step(
        params, tokens, q_lens, state, cfg, attn=attn, group_id=group_id,
        shared_table=shared_table, shared_lens=shared_lens)
    choice = sample_logits(logits, key, temperature=temperature,
                           top_k=top_k, top_p=top_p, nan_sentinel=True)
    return choice, state


@partial(jax.jit,
         static_argnames=("cfg", "k", "attn", "temperature",
                          "top_k", "top_p"),
         donate_argnums=(3,))
def multi_step_decode(params, first_toks, q_lens, state: PagedState, rng,
                      cfg: ModelConfig, *, k: int, attn: str = "ragged",
                      temperature: float = 0.0, top_k=None, top_p=None):
    """K pure-decode ticks fused into ONE jitted lax.scan launch.

    The scan body is the un-jitted tick — _ragged_model_step at q_len 1
    per live slot, one jax.random.split, one sample_logits — so the
    split sequence and every slot's per-row sampling noise are exactly
    what k consecutive synchronous ticks would consume
    (jax.random.categorical's noise depends only on (key, shape, row),
    never on other rows' logits).  The compile key includes the static
    trip count k, so each fusion depth is its own program.

    first_toks [slots] int32 — each live slot's pending next token (the
    previous tick's sampled choice, possibly still in flight on device).
    q_lens     [slots] int32 — 1 for live slots, 0 idle; constant across
               the k steps (eligibility: pure decode, no admission or
               retirement possible inside the window).

    Returns (choices [k, slots] int32, new PagedState with lengths
    advanced by k per live slot, rng after k splits).  A NaN-poisoned
    row samples the -1 sentinel, same as the synchronous path."""
    def body(carry, _):
        toks, st, r = carry
        logits, st = _ragged_model_step(params, toks[:, None], q_lens,
                                        st, cfg, attn=attn)
        r, key = jax.random.split(r)
        choice = sample_logits(logits, key, temperature=temperature,
                               top_k=top_k, top_p=top_p, nan_sentinel=True)
        return (choice, st, r), choice

    (_, state, rng), choices = jax.lax.scan(
        body, (first_toks, state, rng), None, length=k)
    return choices, state, rng


def assign_pages(state: PagedState, slot: int, ids) -> PagedState:
    """Host-side: point `slot`'s table row at freshly acquired pages (the
    engine reserves a request's FULL lifetime at admission, before any
    token lands).  The slot's length stays 0 until the first chunk; the
    row must be empty (retired) first."""
    if not ids:
        return state
    if int(np.asarray(state.lengths)[slot]) != 0:
        raise RuntimeError(f"slot {slot} is still live; free_slot first")
    # tiny host-side table edit: one readback + one upload beats op-by-op
    # .at[].set dispatches (~1.5ms each un-jitted) — admission waves are
    # device-idle windows, so their host cost is pure serve.host_gap
    table = np.asarray(state.page_table).copy()
    table[slot, :len(ids)] = np.asarray(ids, np.int32)
    return state._replace(page_table=jnp.asarray(table))


@partial(jax.jit, donate_argnums=(0,))
def _copy_pages_jit(state: PagedState, src, dst):
    """Device-side page duplication for copy-on-write: every layer's K/V
    (and, on quantized pools, the per-token dequant scales) at pages
    src[i] is copied to pages dst[i] in ONE program — a privatized page
    column is never separated from its scale column.  src/dst are traced
    int32 [n] — one program per copy width, and CoW events copy one page
    at a time, so exactly one program in practice."""
    k_pages = tuple(kp.at[dst].set(kp[src]) for kp in state.k_pages)
    v_pages = tuple(vp.at[dst].set(vp[src]) for vp in state.v_pages)
    k_scales = v_scales = None
    if state.k_scales is not None:
        k_scales = tuple(s.at[dst].set(s[src]) for s in state.k_scales)
        v_scales = tuple(s.at[dst].set(s[src]) for s in state.v_scales)
    return state._replace(k_pages=k_pages, v_pages=v_pages,
                          k_scales=k_scales, v_scales=v_scales)


def cow_pages(state: PagedState, pool: PagePool, slot: int,
              n_tokens: int, cache=None):
    """Copy-on-write barrier: make every page that will receive K/V writes
    for `slot`'s next `n_tokens` tokens PRIVATE (refcount 1) before the
    jitted step scatters into it.

    The scatter in ragged_model_step targets table columns
    lengths//page .. (lengths+n_tokens-1)//page; any of those pages the
    allocator holds at refcount > 1 (pinned by the prefix cache and/or
    other slots) is copied to a fresh page, the table column is rewritten
    to the copy, and one reference on the shared page is dropped.  Every
    launch MUST run behind this barrier — burstlint's `pagepool-cow-safe`
    rule proves the post-barrier invariant (no scatter target at
    refcount > 1) on a live shared workload.

    Returns (state, copies) where copies is [(col, shared_pid, new_pid)].
    Raises RuntimeError if the pool cannot supply a replacement page even
    after evicting unpinned cache pages (`cache` optional).
    """
    if n_tokens <= 0:
        return state, []
    page = state.k_pages[0].shape[2]
    length = int(state.lengths[slot])
    first, last = length // page, (length + int(n_tokens) - 1) // page
    row = np.asarray(state.page_table[slot])
    copies = []
    for col in range(first, min(last, len(row) - 1) + 1):
        pid = int(row[col])
        if pid == 0 or pool.refcount(pid) <= 1:
            continue
        if pool.available < 1 and cache is not None:
            cache.evict(1)
        if pool.available < 1:
            raise RuntimeError(
                f"copy-on-write for slot {slot} col {col}: pool exhausted "
                f"(page {pid} shared at refcount {pool.refcount(pid)})")
        (new,) = pool.acquire(1)
        state = _copy_pages_jit(state, jnp.asarray([pid], jnp.int32),
                                jnp.asarray([new], jnp.int32))
        state = state._replace(
            page_table=state.page_table.at[slot, col].set(new))
        pool.release([pid])
        copies.append((col, pid, new))
    return state, copies


def free_slot(state: PagedState, pool: PagePool, slot: int) -> PagedState:
    """Host-side: release EVERY page in `slot`'s table row and empty it.

    Unlike paged_decode.retire_slot this does NOT early-return on length
    0 — the ragged engine assigns pages at admission, before the first
    prefill chunk lands, so a slot can hold pages at length 0 (mid-
    admission rollback) and they must not leak."""
    return free_slots(state, pool, [slot])


def free_slots(state: PagedState, pool: PagePool, slots) -> PagedState:
    """Batched free_slot: one table readback + one upload no matter how
    many slots retire this tick.  Retire waves are device-idle windows
    (the pipelined engine cannot speculate across them), so their host
    cost is pure serve.host_gap — per-slot .at[].set dispatches were the
    single largest contributor before batching."""
    slots = list(slots)
    if not slots:
        return state
    table = np.asarray(state.page_table).copy()
    lengths = np.asarray(state.lengths).copy()
    for slot in slots:
        ids = [int(i) for i in table[slot] if i != 0]
        if ids:
            pool.release(ids)
        table[slot] = 0
        lengths[slot] = 0
    return state._replace(lengths=jnp.asarray(lengths),
                          page_table=jnp.asarray(table))
