"""The million-token handoff: ring-sharded prefill whose K/V lands
DIRECTLY in pool pages, feeding sequence-parallel paged decode.

The long-context serving story has three acts (ROADMAP items 3/4):

  1. PREFILL at ring scale: the training forward (burst ring attention
     over the `sp` axes, fused_ring on hardware / scan ring elsewhere —
     cfg.attn_backend picks, exactly as in training) absorbs the prompt.
  2. HANDOFF: each layer's rope'd K/V is scattered straight from the
     ring-sharded activations into pool pages — in LAYOUT order, with NO
     re-layout copy.  Page p simply holds layout positions
     [p·page, (p+1)·page); the page table records which pool page that
     is.  A million-token prompt never materializes a natural-order
     cache.
  3. DECODE sequence-parallel: models/dist_decode.dist_paged_decode_step
     shards the POOL's page dim over the same axes; each device attends
     the table entries whose pages it owns and the partials LSE-merge.

Skipping the re-layout is correct because decode attends EVERY cached
position — validity is "is this table entry a real token", never an
ordering — and full-visibility attention is permutation-invariant.  That
argument needs cfg.window=None (a sliding window IS an ordering), which
both ends enforce.

The single-host engine (RaggedServeEngine) and this path share the same
PagedState/PagePool, so a handed-off slot can also be decoded by the
plain paged kernels when the pool lives on one chip (tested both ways).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.dist_decode import dist_paged_decode_step
from ..models.paged_decode import (
    PagedState, PagePool, _scatter_pages, _write_table_row,
    provision_capacity,
)
from ..models.transformer import (
    ModelConfig, _attn_out, _mlp, _qkv_proj, _rms_norm,
)
from ..parallel import layouts
from ..parallel.burst import burst_attn


def check_handoff_preconditions(state: PagedState, pool: PagePool,
                                slot: int, n_tokens: int,
                                cfg: ModelConfig, *, steps: int = 0) -> int:
    """Validate EVERY admission precondition for a handoff — prompt
    shape, window mode, slot state, table width, and pool availability
    for prefill pages PLUS the decode budget (`steps`) — before a single
    page is acquired or a single state field mutated.

    Callers rely on the zero-mutation guarantee: any raise here leaves
    pool occupancy and state byte-for-byte unchanged, so a rejected
    request can be retried or re-routed with nothing to clean up.
    Returns the number of prefill pages the prompt needs."""
    page = int(state.k_pages[0].shape[2])
    if cfg.window is not None:
        raise ValueError("ring_prefill_to_pages requires cfg.window=None "
                         "(layout-order pages; see module docstring)")
    if n_tokens <= 0:
        raise ValueError(f"empty prompt (n_tokens={n_tokens})")
    if n_tokens % page:
        raise ValueError(f"prompt length {n_tokens} must be a multiple of "
                         f"the page size {page} for the direct-scatter "
                         f"handoff")
    if steps < 0:
        raise ValueError(f"negative decode budget ({steps})")
    if not 0 <= slot < state.lengths.shape[0]:
        raise ValueError(f"slot {slot} out of range "
                         f"[0, {state.lengths.shape[0]})")
    n_prefill = n_tokens // page
    n_total = -(-(n_tokens + steps) // page)
    if n_total > state.page_table.shape[1]:
        raise ValueError(f"request needs {n_total} pages (prompt "
                         f"{n_prefill} + decode budget {steps} tokens) > "
                         f"table width {state.page_table.shape[1]}")
    if int(state.lengths[slot]) != 0:
        raise RuntimeError(f"slot {slot} is still live; retire it first")
    if pool.available < n_total:
        raise RuntimeError(f"page pool exhausted: want {n_total}, have "
                           f"{pool.available}")
    return n_prefill


def ring_prefill_to_pages(params, tokens, state: PagedState, pool: PagePool,
                          slot: int, cfg: ModelConfig, mesh):
    """Absorb a [S] prompt into batch slot `slot` with the ring-sharded
    forward, landing each layer's K/V directly in pool pages.

    Host wrapper: acquires S/page pages, runs the jitted ring pass
    (burst_attn prefill + paged scatter in layout order), rewrites the
    slot's table row.  Returns (last-token logits [vocab] fp32, state).
    S must be a page multiple (ring shards are page-aligned by
    construction: S divides by the sp world and page | S/world in any
    deployment this path targets) and cfg.window must be None (see the
    module docstring's permutation-invariance argument).  All
    preconditions are checked up-front (`check_handoff_preconditions`);
    any rejection leaves the pool untouched."""
    t = int(tokens.shape[0])
    n_need = check_handoff_preconditions(state, pool, slot, t, cfg)
    ids = pool.acquire(n_need)
    try:
        logits, state = _ring_prefill_jit(
            params, jnp.asarray(tokens)[None, :], state,
            jnp.asarray(ids, jnp.int32), jnp.int32(slot), cfg, mesh)
    except Exception:
        pool.release(ids)
        raise
    return logits[0], state


@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(2,))
def _ring_prefill_jit(params, tokens, state: PagedState, page_ids, slot,
                      cfg: ModelConfig, mesh):
    """dist_prefill's forward with the cache capture replaced by a paged
    scatter: K/V stays in layout order end to end — the pages ARE the
    sharded cache."""
    b, s = tokens.shape
    world = 1
    for a in cfg.seq_axes:
        world *= mesh.shape.get(a, 1)
    perm = layouts.seq_permutation(cfg.layout, s, world)
    pos = jnp.broadcast_to(jnp.asarray(perm, jnp.int32)[None, :], (b, s))
    tokens_l = jnp.take(tokens, jnp.asarray(perm), axis=1)

    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    act_spec = NamedSharding(mesh, P(cfg.batch_axis, seq_spec, None))
    kv_spec = NamedSharding(mesh, P(cfg.batch_axis, None, seq_spec, None))
    quant = state.k_scales is not None

    x = params["embed"].astype(cfg.dtype)[tokens_l]
    x = lax.with_sharding_constraint(x, act_spec)
    k_pools, v_pools, k_scs, v_scs = [], [], [], []
    for li, (p, kp, vp) in enumerate(zip(params["layers"], state.k_pages,
                                         state.v_pages)):
        q, k, v = _qkv_proj(p, x, pos, cfg)
        k = lax.with_sharding_constraint(k.astype(cfg.dtype), kv_spec)
        v = lax.with_sharding_constraint(v.astype(cfg.dtype), kv_spec)
        o = burst_attn(
            q, k, v, mesh=mesh, seq_axes=cfg.seq_axes, causal=cfg.causal,
            layout=cfg.layout, backend=cfg.attn_backend,
            block_q=cfg.block_q, block_kv=cfg.block_kv,
            batch_axes=cfg.batch_axis, head_axes=cfg.head_axis,
            window=cfg.window,
        )
        # THE handoff: layout-order K/V -> pool pages, no re-layout copy
        kp2, ks2 = _scatter_pages(kp, k, page_ids,
                                  state.k_scales[li] if quant else None)
        vp2, vs2 = _scatter_pages(vp, v, page_ids,
                                  state.v_scales[li] if quant else None)
        k_pools.append(kp2)
        v_pools.append(vp2)
        k_scs.append(ks2)
        v_scs.append(vs2)
        x = x + _attn_out(p, o)
        m, _ = _mlp(p, x, cfg, mesh, inference=True)
        x = lax.with_sharding_constraint(x + m, act_spec)

    xf = _rms_norm(x, params["final_norm"])
    # the last NATURAL token sits at layout position inv_perm[s-1] — a
    # host-side constant (perm is a layout table, never traced)
    last_pos = layouts.inverse_permutation(perm)[s - 1]
    logits = jnp.einsum("bd,vd->bv", xf[:, last_pos], params["lm_head"],
                        preferred_element_type=jnp.float32)
    table = _write_table_row(state, slot, page_ids)
    lengths = state.lengths.at[slot].set(s)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), table, lengths,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


def handoff_generate(params, prompt, state: PagedState, pool: PagePool,
                     cfg: ModelConfig, mesh, *, steps: int, slot: int = 0,
                     temperature: float = 0.0, top_k=None, top_p=None,
                     rng=None):
    """End-to-end million-token path on one slot: ring prefill into pool
    pages, provision the decode budget, then `steps` sequence-parallel
    paged decode steps.  Returns ([steps] tokens, final state).

    Greedy/sampled semantics are decode.sample_logits's; the decode loop
    is a python loop over one jitted step (static shapes — no retrace).

    Admission is all-or-nothing: the decode budget is validated together
    with the prefill's page needs BEFORE the ring pass runs, so a
    request whose budget cannot fit (table width or pool availability)
    rejects with zero pool mutation — previously the provision ran after
    prefill had already acquired pages and made the slot live, leaking
    them on rejection."""
    from ..models.decode import sample_logits

    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    check_handoff_preconditions(state, pool, slot, int(prompt.shape[0]),
                                cfg, steps=steps)
    rng = jax.random.PRNGKey(0) if rng is None else rng
    last_logits, state = ring_prefill_to_pages(
        params, prompt, state, pool, slot, cfg, mesh)
    state = provision_capacity(state, pool, slot, steps)

    @jax.jit
    def pick(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p, nan_sentinel=True)

    slots = state.lengths.shape[0]
    keys = jax.random.split(rng, steps + 1)
    tok = int(np.asarray(pick(last_logits[None, :], keys[0]))[0])
    if tok < 0:
        raise RuntimeError("handoff prefill logits are NaN-poisoned")
    out = [tok]
    feed = np.zeros((slots,), np.int32)
    for i in range(steps - 1):
        feed[slot] = out[-1]
        logits, state = dist_paged_decode_step(
            params, jnp.asarray(feed), state, cfg, mesh)
        tok = int(np.asarray(pick(logits[slot][None, :], keys[i + 1]))[0])
        if tok < 0:
            raise RuntimeError(
                f"handoff decode step {i} logits are NaN-poisoned")
        out.append(tok)
    return out, state


def handoff_decode(params, state: PagedState, cfg: ModelConfig, mesh, *,
                   slot: int, last_token: int, n_steps: int, journal=None,
                   rid: int = 0):
    """Resumable greedy decode on an already-provisioned handoff slot:
    `n_steps` sequence-parallel paged steps continuing from `last_token`
    (the newest token already in the stream — prefill-sampled or
    journal-recovered).  Returns ([n_steps] tokens, final state).

    This is the crash-consistency surface for the million-token path:
    handoff_generate fused prefill+decode in one call, so a fault left
    nothing to resume FROM.  Here the caller owns the split — after
    `ring_prefill_to_pages` + `provision_capacity` (or after
    `load_paged_snapshot` rebuilt the state from a checkpoint), decode
    proceeds in restartable strides, and each emitted token can be
    journaled write-ahead (`journal.tokens(rid, [tok])` + sync per step)
    so a killed decode resumes from its last durable token instead of
    re-burning the ring prefill.  Greedy only (argmax == sample_logits
    at temperature 0): a resumed stream must be the continuation the
    dead decode would have produced."""
    slots = state.lengths.shape[0]
    feed = np.zeros((slots,), np.int32)
    cur = int(last_token)
    out = []
    for i in range(n_steps):
        feed[slot] = cur
        logits, state = dist_paged_decode_step(
            params, jnp.asarray(feed), state, cfg, mesh)
        row = np.asarray(logits[slot])
        if np.isnan(row).any():
            raise RuntimeError(
                f"handoff decode step {i} logits are NaN-poisoned: slot "
                f"{slot} stepped without provisioned capacity")
        cur = int(row.argmax())
        out.append(cur)
        if journal is not None:
            journal.tokens(rid, [cur])
            journal.sync()
    return out, state
