"""Device-side ring telemetry: a purely functional stats pytree.

Everything else in `burst_attn_tpu.obs` is host-only by contract (the
burstlint `obs-jit-safe` rule proves no registry/span call is reachable
under jit).  That contract makes the *inside* of a ring step invisible:
per-round work distribution, mask occupancy under the causal layouts,
softmax-stat health, fused-ring slot behavior — all of it lives in the
compiled program, where host instrumentation must never go.

`DevStats` closes the gap without breaking the contract.  It is a NamedTuple
of plain device arrays that the ring forward accumulates IN-GRAPH
(`burst_attn(..., collect_stats=True)` returns `(out, DevStats)`): no host
callbacks, no clocks, no registry writes — just extra pure equations whose
cost is O(rounds * s_local) scalar work, invisible next to the attention
itself.  After the step the caller folds the (now concrete) arrays into the
host registry with `DevStats.publish(...)` — the device->host hop happens at
the host boundary, exactly where `obs-jit-safe` wants it.  The companion
burstlint rule `devstats-pure` (analysis/obscheck.py) proves both halves of
the bargain: the stats-enabled forward/backward traces contain zero
host-callback primitives, and the stats-OFF trace is bit-identical to the
plain (pre-devstats) ring program.

Per-shard, every field is a scalar (except `slot_use`); at the
`burst_attn` boundary the shards are stacked over the ring axis, so the
caller sees per-device arrays of leading length `world`:

  rounds         executed ring rounds (truncated rings count live schedule)
  rounds_live    rounds whose mask had ANY attending pair (ops/masks.spec_live)
  attn_pairs     attended (q, kv) pairs summed over rounds (f32)
  total_pairs    s_q * s_kv summed over executed rounds (occupancy denom)
  flops          ~4 * head_dim * attn_pairs — the per-device balance
                 measure; the burstcost roofline carries the same algebra
                 in closed form (analysis/costmodel.pass_flops), with the
                 cost-model-consistent lint rule pinning the closed-form
                 pair count to the per-round sum these counters integrate
  m_max          max running row-max after the ring (scan ring only; the
                 fused kernel keeps m internal — reported as -inf there)
  lse_min/max    finite range of the final log-sum-exp
  nonfinite_lse  count of nan/+inf lse entries (-inf is a legal fully-masked
                 row, not an error)
  nonfinite_acc  count of non-finite accumulator/output entries
  fused_rounds   rounds executed inside the fused RDMA kernel (0 on scan)
  rounds_elided  rounds the occupancy compiler removed from the schedule
                 entirely (windowed/segment-bounded contig rings); these
                 never launched, unlike (rounds - rounds_live) which ran
                 fully masked
  slot_use       [MAX_SLOTS] per-KV-slot consume counts from the fused
                 forward kernel's in-kernel scalar output (zeros on the
                 scan path)
  slot_use_bwd   [MAX_SLOTS] per-slot bundle consume counts from the fused
                 BACKWARD kernel (ops/fused_ring_bwd.py), emitted through
                 the same SMEM scalar-output channel.  Zeros on the scan
                 path AND on the autodiff path: custom_vjp cotangents
                 cannot carry telemetry forward in time, so these counters
                 only populate via the direct `fused_ring_bwd(...,
                 collect_stats=True)` call (tests, offline audits)

The split of labor per causal layout is visible directly: zigzag/striped
devices report near-equal `attn_pairs` (the load-balancing the layouts
exist for), a contig ring reports the raw triangle imbalance, and a
windowed contig ring shows the truncated round count.
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

# Fixed width of the per-device slot_use vector so the pytree structure is
# static across configs (a fused kernel with fewer slots zero-pads; the scan
# path reports all zeros).  Matches the largest kv_slots in ops/tuning.py
# with headroom.
MAX_SLOTS = 8

_NEG_INF = float("-inf")
_POS_INF = float("inf")


class DevStats(NamedTuple):
    """In-graph ring telemetry (see module docstring for field semantics).

    A pytree of device arrays: per-shard scalars inside shard_map, stacked
    to a leading `world` axis at the `burst_attn` boundary."""

    rounds: jnp.ndarray          # i32
    rounds_live: jnp.ndarray     # i32
    attn_pairs: jnp.ndarray      # f32
    total_pairs: jnp.ndarray     # f32
    flops: jnp.ndarray           # f32
    m_max: jnp.ndarray           # f32
    lse_min: jnp.ndarray         # f32
    lse_max: jnp.ndarray         # f32
    nonfinite_lse: jnp.ndarray   # i32
    nonfinite_acc: jnp.ndarray   # i32
    fused_rounds: jnp.ndarray    # i32
    # rounds the occupancy compiler ELIDED from the schedule (windowed /
    # length-bounded packed-segment contig rings): world minus the
    # compiled round count.  Executed-vs-live accounting: rounds +
    # rounds_elided == world on single-ring schedules, and an elided
    # round never launched — no RDMA, no sweep, no slot traffic — which
    # is what distinguishes this counter from (rounds - rounds_live),
    # the rounds that RAN fully masked.
    rounds_elided: jnp.ndarray   # i32
    slot_use: jnp.ndarray        # i32[MAX_SLOTS]
    slot_use_bwd: jnp.ndarray    # i32[MAX_SLOTS]
    # second-direction banks of the schedule-IR kernels: the ccw ring of a
    # counter-rotating (bidi) topology, or the double ring's inter
    # prefetch bank.  Zeros for uni schedules and on the scan path; the
    # published counter labels these rows dir="ccw" next to the primary
    # banks' dir="cw" so the bidirectional traffic split is verifiable on
    # device (docs/observability.md).
    slot_use_ccw: jnp.ndarray      # i32[MAX_SLOTS]
    slot_use_bwd_ccw: jnp.ndarray  # i32[MAX_SLOTS]
    # finite-range gauge of the wire quantizer (cfg.wire_dtype): the
    # largest |value| the symmetric per-block quantization mapped to its
    # top code this dispatch.  0.0 on the dense wire.  A growing gauge
    # next to a fixed-range wire dtype means blocks are saturating —
    # observable here rather than silently clipped on the link.
    quant_absmax: jnp.ndarray      # f32

    def publish(self, registry=None, *, labels: Optional[dict] = None):
        """Fold concrete (post-step) stats into a host metrics registry.

        HOST-SIDE ONLY: forces the device arrays to numpy — call it after
        the step, never under a trace (the burstlint `obs-jit-safe` /
        `devstats-pure` pair keeps this honest).  Per-device gauges carry a
        `device` label (ring position); cross-device health extrema and the
        slot/nonfinite counters are aggregated.  Returns the registry."""
        import numpy as np

        from .registry import default_registry

        reg = registry if registry is not None else default_registry()
        base = dict(labels or {})
        leaves = {f: np.asarray(getattr(self, f), dtype=np.float64)
                  for f in self._fields}
        if leaves["rounds"].ndim == 0:  # per-shard stats published directly
            leaves = {f: a[None, ...] for f, a in leaves.items()}
        world = leaves["rounds"].shape[0]

        for dev in range(world):
            lab = dict(base, device=dev)
            reg.gauge("devstats.rounds",
                      "executed ring rounds per device").set(
                leaves["rounds"][dev], **lab)
            reg.gauge("devstats.rounds_live",
                      "rounds with any attending pair").set(
                leaves["rounds_live"][dev], **lab)
            reg.gauge("devstats.rounds_elided",
                      "rounds the occupancy compiler removed from the "
                      "schedule (never launched)").set(
                leaves["rounds_elided"][dev], **lab)
            total = leaves["total_pairs"][dev]
            occ = leaves["attn_pairs"][dev] / total if total > 0 else 0.0
            reg.gauge("devstats.mask_occupancy",
                      "attended fraction of executed tile area").set(occ,
                                                                     **lab)
            reg.gauge("devstats.flops",
                      "attention flop estimate per device").set(
                leaves["flops"][dev], **lab)

        fl = leaves["flops"]
        mean = float(fl.mean())
        reg.gauge("devstats.flop_imbalance",
                  "max/mean per-device attention flops (1.0 = balanced)"
                  ).set(float(fl.max()) / mean if mean > 0 else 0.0, **base)
        reg.gauge("devstats.m_max",
                  "max running row-max across devices (scan ring)").set(
            float(leaves["m_max"].max()), **base)
        reg.gauge("devstats.lse_min").set(float(leaves["lse_min"].min()),
                                          **base)
        reg.gauge("devstats.lse_max").set(float(leaves["lse_max"].max()),
                                          **base)
        reg.counter("devstats.nonfinite",
                    "non-finite softmax-state entries seen, by array").inc(
            float(leaves["nonfinite_lse"].sum()), which="lse", **base)
        reg.counter("devstats.nonfinite").inc(
            float(leaves["nonfinite_acc"].sum()), which="acc", **base)
        reg.counter("devstats.fused_rounds",
                    "ring rounds executed inside the fused RDMA kernel").inc(
            float(leaves["fused_rounds"].sum()), **base)
        for field, pass_, dir_ in (("slot_use", "fwd", "cw"),
                                   ("slot_use_bwd", "bwd", "cw"),
                                   ("slot_use_ccw", "fwd", "ccw"),
                                   ("slot_use_bwd_ccw", "bwd", "ccw")):
            slot_tot = leaves[field].sum(axis=0)
            for j in range(slot_tot.shape[0]):
                if slot_tot[j]:
                    reg.counter(
                        "devstats.slot_use",
                        "fused-ring chunk/bundle consumes per comm slot, "
                        "by pass and ring direction").inc(
                        float(slot_tot[j]), slot=j, dir=dir_, **base,
                        **{"pass": pass_})
        reg.gauge("devstats.quant_absmax",
                  "largest |value| the wire quantizer mapped to its top "
                  "code (0 = dense wire; watch for saturation)").set(
            float(leaves["quant_absmax"].max()), **base)
        reg.counter("devstats.publishes",
                    "DevStats pytrees folded into the registry").inc()
        return reg


def _slot_vec(slot_use):
    """Zero-pad a [.., slots] counter vector to the static MAX_SLOTS width
    (None = all zeros, the scan path's value)."""
    if slot_use is None:
        return jnp.zeros((MAX_SLOTS,), jnp.int32)
    return jnp.zeros((MAX_SLOTS,), jnp.int32).at[:slot_use.shape[-1]].set(
        jnp.asarray(slot_use, jnp.int32).reshape(-1))


def ring_stats(rounds, rounds_live, attn_pairs, total_pairs, head_dim,
               m, lse, acc, fused_rounds=0, rounds_elided=0, slot_use=None,
               slot_use_bwd=None, slot_use_ccw=None,
               slot_use_bwd_ccw=None, quant_absmax=0.0) -> DevStats:
    """Assemble a per-shard DevStats from ring results (traced context).

    `m` may be None (fused kernel: the row max never leaves the kernel);
    `acc` is the f32 accumulator on the scan path and the finalized output
    on the fused path — either way, non-finite entries mean the softmax
    went wrong.  `lse` -inf entries are legal (fully-masked rows) and are
    excluded from the finite range but not counted as corruption.
    `slot_use_bwd` carries the fused backward kernel's bundle slot-consume
    counters when the caller ran it with collect_stats (see the field
    docstring above)."""
    i32 = jnp.int32
    f32 = jnp.float32
    attn_pairs = jnp.asarray(attn_pairs, f32)
    finite = jnp.isfinite(lse)
    stats = DevStats(
        rounds=jnp.asarray(rounds, i32),
        rounds_live=jnp.asarray(rounds_live, i32),
        attn_pairs=attn_pairs,
        total_pairs=jnp.asarray(total_pairs, f32),
        flops=attn_pairs * (4.0 * head_dim),
        m_max=(jnp.asarray(_NEG_INF, f32) if m is None
               else jnp.max(m).astype(f32)),
        lse_min=jnp.min(jnp.where(finite, lse, _POS_INF)).astype(f32),
        lse_max=jnp.max(jnp.where(finite, lse, _NEG_INF)).astype(f32),
        nonfinite_lse=jnp.sum(
            jnp.isnan(lse) | (lse == _POS_INF)).astype(i32),
        nonfinite_acc=jnp.sum(~jnp.isfinite(acc)).astype(i32),
        fused_rounds=jnp.asarray(fused_rounds, i32),
        rounds_elided=jnp.asarray(rounds_elided, i32),
        slot_use=_slot_vec(slot_use),
        slot_use_bwd=_slot_vec(slot_use_bwd),
        slot_use_ccw=_slot_vec(slot_use_ccw),
        slot_use_bwd_ccw=_slot_vec(slot_use_bwd_ccw),
        quant_absmax=jnp.asarray(quant_absmax, f32),
    )
    # telemetry is non-differentiable by definition: zero the tangents here
    # so downstream cross_reduce/merge arithmetic never asks autodiff for
    # pmax/pmin rules and grads through the attention output stay untouched
    return jax.tree.map(lax.stop_gradient, stats)


# per-field cross-device reduction when extra (batch/head) mesh axes ride
# alongside the ring: counts sum, extrema max/min — so the published
# per-ring-position stats cover the whole shard group at that position
_REDUCE_MAX = ("m_max", "lse_max", "quant_absmax")
_REDUCE_MIN = ("lse_min",)


def cross_reduce(stats: DevStats, axes) -> DevStats:
    """Reduce per-shard stats over non-ring mesh axes (inside shard_map).

    `axes`: names of size>1 batch/head axes; empty = no-op.  Sums are the
    right unit for counters (total pairs across the replica group at one
    ring position), extrema for the health fields."""
    axes = tuple(axes)
    if not axes:
        return stats
    out = {}
    for f in stats._fields:
        v = getattr(stats, f)
        if f in _REDUCE_MAX:
            out[f] = lax.pmax(v, axes)
        elif f in _REDUCE_MIN:
            out[f] = lax.pmin(v, axes)
        else:
            out[f] = lax.psum(v, axes)
    return DevStats(**out)


def expand_device_axis(stats: DevStats) -> DevStats:
    """Per-shard scalars -> leading [1] axis, so a shard_map out_spec over
    the ring axis stacks them into per-device arrays of length `world`."""
    return jax.tree.map(lambda a: a[None, ...], stats)


def merge(a: DevStats, b: DevStats) -> DevStats:
    """Fold two DevStats (e.g. successive transformer layers): counts add,
    extrema max/min — same semantics as cross_reduce, host/trace agnostic."""
    out = {}
    for f in a._fields:
        va, vb = getattr(a, f), getattr(b, f)
        if f in _REDUCE_MAX:
            out[f] = jnp.maximum(va, vb)
        elif f in _REDUCE_MIN:
            out[f] = jnp.minimum(va, vb)
        else:
            out[f] = va + vb
    return DevStats(**out)
