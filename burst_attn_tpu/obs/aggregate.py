"""Cross-process obs aggregation: merge per-process JSONL exports.

A multi-host job (jax.process_count() > 1) exports ONE JSONL snapshot file
per process (`obs.export_jsonl` tags the `meta` header with that process's
`process_index`).  This module folds those per-process final states into a
single job-level report — the "one merged metrics view per job" the serving
north star needs — with Prometheus-style semantics per metric kind:

  counters    SUM across processes (each process counted disjoint events)
  gauges      last-wins is only meaningful WITHIN a process, so gauges keep
              a `process_index` label instead of being merged away
  histograms  bucket-wise ADD when the bucket edges agree (they do for any
              same-binary job); edge-mismatched children fall back to
              per-process children with a `process_index` label
  spans       concatenated, each tagged `process_index`
  traces      joined by trace_id across processes (deterministic span ids
              dedup re-exports); `build_trace_trees` folds them into
              per-request trees flagged for completeness/truncation
  exemplars   worst-value-wins per (metric, bucket)

`--by-process` skips the cross-process arithmetic entirely: every metric
child keeps its own `process_index` label (the per-process drill-down view).

CLI:  python -m burst_attn_tpu.obs --merge 'results/obs*.jsonl'
                                   [--by-process] [--json | --prom]
"""

import glob
import json
import os
from typing import Dict, List, Sequence, Tuple

from .__main__ import merge_records


def load_records_tolerant(path: str) -> Tuple[List[dict], int]:
    """Like __main__.load_records, but a bad FINAL line is skipped with a
    count instead of raising — the signature of a snapshot truncated by a
    kill (SIGKILL mid-write leaves a partial last line; everything before
    it is a complete, fsynced earlier snapshot).  A bad line anywhere
    ELSE still raises ValueError: mid-file corruption is not truncation
    and must stay loud.  Returns (records, n_skipped)."""
    with open(path, encoding="utf-8") as f:
        lines = [(i, line.strip()) for i, line in enumerate(f, 1)]
    lines = [(i, line) for i, line in lines if line]
    records: List[dict] = []
    for pos, (i, line) in enumerate(lines):
        bad = None
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            bad = f"{path}:{i}: not JSON: {e}"
            rec = None
        if bad is None and (not isinstance(rec, dict) or "kind" not in rec):
            bad = f"{path}:{i}: not an obs record: {line[:80]}"
        if bad is not None:
            # only a bad FINAL line with valid records before it reads as
            # truncation — a file that is nothing but garbage stays loud
            if pos == len(lines) - 1 and records:
                return records, 1
            raise ValueError(bad)
        records.append(rec)
    return records, 0


def resolve_files(patterns: Sequence[str]) -> List[str]:
    """Expand globs (sorted, deduped).  Literal paths pass through."""
    out = []
    for pat in patterns:
        hits = sorted(glob.glob(pat))
        out += hits if hits else ([pat] if os.path.exists(pat) else [])
    seen, files = set(), []
    for f in out:
        if f not in seen:
            seen.add(f)
            files.append(f)
    return files


def load_process_states(files: Sequence[str]):
    """Per-process final states: [(process_label, metrics, spans, meta)].

    Each file is one process's (possibly multi-snapshot) export; within a
    file the existing last-wins merge applies.  The process label comes
    from the newest `meta` record's `process_index` when present (the
    exporter writes it), else the file's position in the sorted list —
    and collides are disambiguated by position so two re-exports of
    process 0 never silently alias."""
    states = []
    used = set()
    for i, path in enumerate(files):
        # tolerant: a killed worker's final partial line is skipped with a
        # `truncated_lines` count (mid-file corruption still raises)
        records, skipped = load_records_tolerant(path)
        if not records:
            continue
        metrics, spans, meta = merge_records(records)
        label = None
        for rec in records:
            if rec.get("kind") == "meta" and "process_index" in rec:
                label = rec["process_index"]  # newest snapshot wins
        if label is None or str(label) in used:
            label = i
        label = str(label)
        used.add(label)
        states.append((label, metrics, spans,
                       dict(meta, file=path, truncated_lines=skipped)))
    return states


def _child_key(rec: dict, extra: Tuple = ()) -> tuple:
    return (rec["kind"], rec.get("name"),
            tuple(sorted((rec.get("labels") or {}).items())) + tuple(extra))


def _tagged(rec: dict, proc: str) -> dict:
    out = dict(rec)
    out["labels"] = dict(rec.get("labels") or {}, process_index=proc)
    return out


def merge_processes(states, by_process: bool = False):
    """Fold per-process final states into one report.

    Returns (metrics, spans, meta) in the same record schema the CLI
    renderers consume.  See the module docstring for per-kind semantics."""
    metrics: Dict[tuple, dict] = {}
    spans: List[dict] = []
    traces: Dict[tuple, dict] = {}
    exemplars: Dict[tuple, dict] = {}
    truncated_procs: List[str] = []
    n_snapshots = 0
    n_truncated = 0
    last_ts = ""
    for proc, proc_metrics, proc_spans, proc_meta in states:
        n_snapshots += proc_meta.get("snapshots", 0)
        n_truncated += proc_meta.get("truncated_lines", 0)
        if proc_meta.get("truncated_lines"):
            truncated_procs.append(proc)
        last_ts = max(last_ts, proc_meta.get("last_ts_utc", ""))
        for rec in proc_spans:
            spans.append(dict(rec, process_index=proc))
        for rec in proc_meta.get("traces", ()):
            # trace spans join ACROSS processes by trace_id; span ids are
            # deterministic per tree, so cross-export re-reads dedup here
            key = (rec.get("trace_id"), rec.get("span_id"))
            traces.setdefault(key, dict(rec, process_index=proc))
        for rec in proc_meta.get("exemplars", ()):
            key = (rec.get("metric"), rec.get("le"))
            have = exemplars.get(key)
            if have is None or rec.get("value", 0) >= have.get("value", 0):
                exemplars[key] = rec
        for rec in proc_metrics:
            kind = rec["kind"]
            if by_process or kind == "gauge":
                # gauges: last-wins is per-process state; a cross-process
                # sum/last would fabricate a value no process ever reported
                tagged = _tagged(rec, proc)
                metrics[_child_key(tagged)] = tagged
                continue
            key = _child_key(rec)
            have = metrics.get(key)
            if have is None:
                metrics[key] = dict(rec, labels=dict(rec.get("labels") or {}))
            elif kind == "counter":
                have["value"] += rec["value"]
            elif kind == "histogram":
                if have.get("bucket_edges") == rec.get("bucket_edges"):
                    have["count"] += rec["count"]
                    have["sum"] += rec["sum"]
                    have["min"] = min(have["min"], rec["min"])
                    have["max"] = max(have["max"], rec["max"])
                    have["bucket_counts"] = [
                        a + b for a, b in zip(have["bucket_counts"],
                                              rec["bucket_counts"])]
                    have["overflow"] = (have.get("overflow", 0)
                                        + rec.get("overflow", 0))
                else:
                    # mismatched edges (mixed binaries): keep both children
                    # apart rather than adding apples to oranges
                    tagged = _tagged(rec, proc)
                    metrics[_child_key(tagged)] = tagged
            else:  # unknown kinds pass through per process
                tagged = _tagged(rec, proc)
                metrics[_child_key(tagged)] = tagged
    meta = {
        "snapshots": n_snapshots,
        "last_ts_utc": last_ts,
        "processes": len(states),
        "process_labels": [s[0] for s in states],
        "n_metrics": len(metrics),
        "n_spans": len(spans),
        "n_traces": len({t.get("trace_id") for t in traces.values()}),
        "truncated_lines": n_truncated,
        "truncated_processes": truncated_procs,
        "traces": list(traces.values()),
        "exemplars": list(exemplars.values()),
    }
    return list(metrics.values()), spans, meta


def build_trace_trees(traces, truncated_processes=()):
    """Group merged trace records into per-request trees, joined by
    trace_id.  Each tree is
    {"trace_id", "spans" (by start time), "complete", "truncated"}:

      complete   the tree has a root (parent_id None) and every span's
                 parent resolves within the tree — the cross-process join
                 actually closed.
      truncated  some contributing process's export lost its final line
                 (the SIGKILL signature `load_records_tolerant` skips) —
                 the tree is read as partial-but-flagged, never silently
                 whole.
    """
    truncated = {str(p) for p in truncated_processes}
    by_trace: Dict[str, List[dict]] = {}
    for rec in traces:
        by_trace.setdefault(rec.get("trace_id"), []).append(rec)
    trees = []
    for trace_id in sorted(by_trace, key=str):
        spans = sorted(by_trace[trace_id], key=lambda s: s.get("start_s", 0))
        ids = {s.get("span_id") for s in spans}
        complete = (any(s.get("parent_id") is None for s in spans)
                    and all(s.get("parent_id") in ids for s in spans
                            if s.get("parent_id") is not None))
        torn = any(str(s.get("process_index")) in truncated for s in spans)
        trees.append({"trace_id": trace_id, "spans": spans,
                      "complete": complete, "truncated": torn})
    return trees


def merge_files(patterns: Sequence[str], by_process: bool = False):
    """Glob -> per-process states -> one merged (metrics, spans, meta).

    Raises FileNotFoundError when the patterns match nothing and ValueError
    on unparseable content (the CLI maps these to exit 1 / 2)."""
    files = resolve_files(patterns)
    if not files:
        raise FileNotFoundError(
            f"no obs exports match {list(patterns)!r}")
    states = load_process_states(files)
    if not states:
        raise FileNotFoundError(
            f"obs exports {files!r} contain no records")
    return merge_processes(states, by_process=by_process)
