"""burst_attn_tpu.obs — unified observability: metrics, spans, logging.

The north-star workloads (heavy serving traffic, long training runs, ring
kernels whose whole value is comm/compute overlap) can only be steered by
evidence; this package is where that evidence accumulates:

  * `registry` — per-process counters / gauges / fixed-bucket histograms
    (thread-safe, host-only), with JSONL and Prometheus-text exporters.
  * `spans` — structured span tracer (context manager + decorator,
    monotonic clocks, parent/child nesting, thread-safe) that doubles as a
    `jax.profiler` annotation so the same names appear in xprof; no-op
    under a jax trace.
  * `logs` — the obs logger (log records counted in the registry) and
    `safe_warn` for teardown paths.
  * CLI — `python -m burst_attn_tpu.obs [--json|--prom]` renders a report
    from a run's JSONL export (bench.py and the runner write
    `results/obs.jsonl`).

Metric catalog and naming conventions: docs/observability.md.

JIT safety contract (enforced by burstlint's `obs-jit-safe` rule): no
registry or span call may be reachable from inside a jit-traced function —
instrumentation lives at host boundaries (dispatch wrappers, engine loops,
bench harnesses).  Counters incremented at TRACE time (e.g. the burst
dispatch counters) advance once per compiled program and are documented as
such.
"""

from . import registry as _registry_mod
from .registry import (
    Counter, Gauge, Histogram, Registry, LATENCY_BUCKETS_S,
    default_registry,
)
from .spans import (
    Span, StepTimer, annotate, completed_spans, current_span, reset_spans,
    span, span_records, traced,
)
from .logs import dropped_messages, get_logger, safe_warn
# request tracing: per-request causal timelines (TraceContext propagation,
# tail-sampled trees, TTFT critical-path analyzer).  OFF by default; the
# submodule import keeps span-vs-trace naming explicit at call sites
# (`trace.record_span`), so only the submodule and its context type are
# re-exported here.
from . import trace
from .trace import TraceContext
# devstats is the deliberately IN-JIT half of obs: a purely functional
# telemetry pytree the ring accumulates in-graph (collect_stats=True) and
# publishes host-side afterwards.  burstlint's obs-jit-safe AST rule
# exempts it by name; the jaxpr rule `devstats-pure` proves its purity.
from . import devstats
from .devstats import DevStats


def counter(name: str, help: str = "") -> Counter:
    """Get-or-create a counter in the default registry."""
    return default_registry().counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return default_registry().gauge(name, help)


def histogram(name: str, help: str = "", buckets=None) -> Histogram:
    return default_registry().histogram(name, help, buckets=buckets)


def snapshot():
    """Every metric child in the default registry as JSON-able dicts."""
    return default_registry().snapshot()


def to_prometheus() -> str:
    return default_registry().to_prometheus()


def _process_index() -> int:
    """This process's multi-host index (0 single-process / pre-jax-init);
    lazy so registry-only users never pay a backend initialization."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — uninitialized backend == process 0
        return 0


def export_jsonl(path: str) -> str:
    """Append a full snapshot (metrics + completed spans) to `path`,
    fsynced, tagged with this process's `process_index` so per-process
    files merge cleanly (`python -m burst_attn_tpu.obs --merge`).  This is
    the artifact `python -m burst_attn_tpu.obs` reads."""
    extra = (span_records() + trace.trace_records()
             + trace.exemplar_records())
    return default_registry().export_jsonl(path,
                                           extra_records=extra,
                                           process_index=_process_index())


def reset() -> None:
    """Clear the default registry, span and trace buffers (tests only)."""
    default_registry().reset()
    reset_spans()
    trace.reset_traces()


__all__ = [
    "Counter", "DevStats", "Gauge", "Histogram", "Registry", "Span",
    "StepTimer", "LATENCY_BUCKETS_S", "TraceContext", "annotate",
    "completed_spans", "counter", "current_span", "default_registry",
    "devstats", "dropped_messages", "export_jsonl", "gauge", "get_logger",
    "histogram", "reset", "reset_spans", "safe_warn", "snapshot", "span",
    "span_records", "to_prometheus", "trace", "traced",
]
