"""Structured span tracing for host-side phases.

`span("serve.run")` / `@traced("eval")` wrap a block with:

  * a monotonic clock (`time.perf_counter_ns`) whose duration feeds the
    registry histogram `span.<name>` — so the CLI report shows aggregate
    count/total/mean per span name with zero extra bookkeeping;
  * parent/child nesting via a per-thread stack (thread-safe by
    construction: each thread nests independently, completed spans land in
    one shared ring buffer under a lock);
  * a `jax.profiler.TraceAnnotation`, so the same names appear on the
    xprof/TensorBoard timeline when a capture (`utils.profiling.trace`) is
    active — one naming convention across obs output and device profiles.

On-device safety: if the calling thread is inside a jax trace (the span
would otherwise record TRACE time and, worse, tempt callers into host
callbacks), `span()` degrades to a pure `jax.named_scope` — the name still
reaches the compiled program's metadata/xprof, but no clock is read and no
registry state is touched.  This is the no-op path the burstlint
`obs-jit-safe` rule assumes; instrumentation is still expected to live at
host boundaries, the degrade just makes an accidental traced call harmless.

`StepTimer` and `annotate` moved here from utils/profiling.py (which keeps
deprecation shims); `trace()` — the XLA profiler capture — stays in
utils/profiling.py since it is about device timelines, not obs state.
"""

import collections
import contextlib
import functools
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax

from .registry import default_registry

# completed spans, newest last; bounded so a long-serving process cannot
# grow without limit (aggregates live in the registry histograms forever)
MAX_SPANS = 4096
_completed = collections.deque(maxlen=MAX_SPANS)
_completed_lock = threading.Lock()
_ids = itertools.count(1)
_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _tracing() -> bool:
    """True when the calling thread is inside a jax trace (jit/scan/vmap
    tracing, abstract eval) — spans must not read clocks or mutate the
    registry there."""
    try:
        return not jax.core.trace_state_clean()
    except Exception:  # noqa: BLE001 — renamed across jax versions
        # unknown tracing state: assume host context (the conservative
        # failure is a trace-time wall-clock read, not a wrong program)
        return False


@dataclass
class Span:
    """One completed span (what the exporter/CLI sees)."""

    name: str
    span_id: int
    parent_id: Optional[int]
    depth: int
    thread: str
    start_s: float          # perf_counter-based, comparable within-process
    duration_s: float
    attrs: Dict[str, object] = field(default_factory=dict)

    def record(self) -> dict:
        return {"kind": "span", "name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "depth": self.depth,
                "thread": self.thread, "start_s": round(self.start_s, 6),
                "duration_s": round(self.duration_s, 9),
                "attrs": self.attrs}


class _LiveSpan:
    """Handle yielded inside a `span()` block; `set(k, v)` attaches attrs."""

    __slots__ = ("name", "span_id", "parent_id", "depth", "attrs")

    def __init__(self, name, span_id, parent_id, depth):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.depth = depth
        self.attrs: Dict[str, object] = {}

    def set(self, key: str, value) -> None:
        self.attrs[key] = value


class _NoopSpan:
    __slots__ = ()
    name = None
    span_id = None
    parent_id = None
    depth = 0
    attrs: Dict[str, object] = {}

    def set(self, key: str, value) -> None:
        return None


_NOOP = _NoopSpan()


@contextlib.contextmanager
def span(name: str, **attrs):
    """Context manager: time a host-side block as a named span.

        with span("serve.step", live=3) as sp:
            ...
            sp.set("admitted", 2)

    Under a jax trace this is a no-op that only applies `jax.named_scope`
    (see module docstring)."""
    if _tracing():
        with jax.named_scope(name):
            yield _NOOP
        return
    stack = _stack()
    parent = stack[-1] if stack else None
    live = _LiveSpan(name, next(_ids),
                     parent.span_id if parent else None, len(stack))
    live.attrs.update(attrs)
    stack.append(live)
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield live
    finally:
        dur = time.perf_counter() - t0
        stack.pop()
        done = Span(name=name, span_id=live.span_id,
                    parent_id=live.parent_id, depth=live.depth,
                    thread=threading.current_thread().name,
                    start_s=t0, duration_s=dur, attrs=live.attrs)
        with _completed_lock:
            _completed.append(done)
        default_registry().histogram("span." + name).observe(dur)


def traced(name: Optional[str] = None):
    """Decorator form of `span`: `@traced("eval")` or bare `@traced()`
    (uses the function's qualname)."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def current_span():
    """The innermost live span on this thread (None at top level)."""
    stack = _stack()
    return stack[-1] if stack else None


def completed_spans(limit: Optional[int] = None) -> List[Span]:
    """Most recent completed spans, oldest first (bounded by MAX_SPANS)."""
    with _completed_lock:
        out = list(_completed)
    return out[-limit:] if limit else out


def span_records(limit: Optional[int] = None) -> List[dict]:
    return [s.record() for s in completed_spans(limit)]


def reset_spans() -> None:
    """Drop the completed-span buffer (tests)."""
    with _completed_lock:
        _completed.clear()


def annotate(name: str):
    """Named region on the xprof timeline only (no clocks, no registry) —
    the raw `jax.profiler.TraceAnnotation`, kept for callers that want the
    profiler mark without obs state (moved from utils/profiling.py)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock step timer that blocks on the step's OUTPUTS at exit so
    device work is included without serializing unrelated async work (a
    global live-array sweep would block on e.g. the next batch's
    host-to-device prefetch and destroy the IO/compute overlap):

        with timer as t:
            state, metrics = step(state, batch)
            t.watch(state)

    Moved here from utils/profiling.py (shim kept there); each completed
    step also feeds the registry histogram `span.step_timer` so step times
    show up in obs exports alongside explicit spans.
    """

    def __init__(self, metric: str = "step_timer"):
        self.times: List[float] = []
        self._metric = "span." + metric
        self._t0: Optional[float] = None
        self._watched = None

    def watch(self, *outputs):
        """Register the step's outputs; exit blocks until they are ready."""
        self._watched = outputs
        return outputs[0] if len(outputs) == 1 else outputs

    def __enter__(self):
        self._watched = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            if self._watched is None:
                raise RuntimeError("StepTimer: call t.watch(outputs) inside the block")
            jax.block_until_ready(self._watched)
            dt = time.perf_counter() - self._t0
            self.times.append(dt)
            default_registry().histogram(self._metric).observe(dt)
        self._watched = None
        return False

    def summary(self, skip_first: int = 1) -> dict:
        """Stats over recorded steps.  The first `skip_first` steps are
        dropped as compile/warmup — unless that would drop EVERYTHING
        (e.g. a single-step run with the default skip_first=1), in which
        case all recorded steps are kept: every field is always finite,
        never NaN, and `steps` reports how many samples the stats cover."""
        ts = self.times[skip_first:] or self.times
        if not ts:
            return {"steps": 0, "mean_s": 0.0, "min_s": 0.0, "max_s": 0.0,
                    "p50_s": 0.0, "std_s": 0.0}
        mean = sum(ts) / len(ts)
        var = sum((t - mean) ** 2 for t in ts) / len(ts)  # 0.0 for 1 step
        return {
            "steps": len(ts),
            "mean_s": mean,
            "min_s": min(ts),
            "max_s": max(ts),
            "p50_s": sorted(ts)[len(ts) // 2],
            "std_s": var ** 0.5,
        }
