"""Obs logger: the one logging setup instrumented code goes through.

Same handler/format contract as the original utils/log_helper.get_logger
(which now delegates here), plus:

  * every emitted record advances the registry counter
    `log.events{level=...}` — noisy subsystems show up in `python -m
    burst_attn_tpu.obs` without grepping stderr;
  * `safe_warn(logger, msg, *args)` — a warning that can NEVER raise, for
    `__del__`/interpreter-teardown paths where the logging machinery itself
    may already be torn down.  Failed emissions are kept in `_DROPPED`
    (inspectable, bounded) instead of being silently lost, which is what
    lets data/loader.py drop its last `silent-except` burstlint
    suppression.

Deliberately standalone (imports nothing from the rest of the package) so
obs can be imported from anywhere — including utils/log_helper and the
data-loader teardown path — without a cycle.
"""

import logging
import sys
from typing import List, Optional

from .registry import default_registry

_FMT = "%(asctime)s %(name)s %(levelname)s: %(message)s"

# messages whose emission failed in safe_warn (teardown); newest last
_DROPPED: List[str] = []
_MAX_DROPPED = 256


class _CountingFilter(logging.Filter):
    """Counts records through the obs registry; never blocks a record."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            default_registry().counter("log.events").inc(
                level=record.levelname)
        except Exception:  # noqa: BLE001 — logging must never raise
            _drop(record.getMessage() if record.args is None else record.msg)
        return True


def _drop(msg) -> None:
    if len(_DROPPED) >= _MAX_DROPPED:
        del _DROPPED[: _MAX_DROPPED // 2]
    _DROPPED.append(str(msg))


def get_logger(name: str, level=logging.INFO,
               file: Optional[str] = None) -> logging.Logger:
    """Per-name logger with stream (and optional file) handlers, configured
    once; every record is counted in `log.events{level=...}`."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        logger.setLevel(level)
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(sh)
        if file:
            fh = logging.FileHandler(file)
            fh.setFormatter(logging.Formatter(_FMT))
            logger.addHandler(fh)
        logger.propagate = False
    if not any(isinstance(f, _CountingFilter) for f in logger.filters):
        logger.addFilter(_CountingFilter())
    return logger


def safe_warn(logger: logging.Logger, msg: str, *args) -> None:
    """logger.warning that cannot raise.  For teardown paths only — normal
    code should call the logger directly so failures surface."""
    try:
        logger.warning(msg, *args)
    except Exception:  # noqa: BLE001 — teardown: logging may be half-gone
        _drop(msg)


def dropped_messages() -> List[str]:
    """Messages safe_warn/counting failed to emit (tests, postmortems)."""
    return list(_DROPPED)
