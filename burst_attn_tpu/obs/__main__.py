"""obs CLI:  python -m burst_attn_tpu.obs [--json] [--prom] [--file PATH]
                                          [--merge GLOB [--by-process]]
                                          [--trace] [--waterfall TRACE_ID]

Renders a report from a run's JSONL export (written by
`obs.export_jsonl`, which bench.py, benchmarks/ring_overlap.py and the
training runner call).  A file may hold several export snapshots (the
exporter appends); the report shows each metric's LAST exported state —
i.e. the final state of the run — and aggregates spans across snapshots.

`--merge GLOB` switches to the MULTI-PROCESS view: every matching file is
one process's export, and the report is the job-level fold (counters sum,
histograms add bucket-wise, gauges keep a `process_index` label — see
obs/aggregate.py).  `--by-process` keeps every child per process instead.

`--trace` renders per-request trace trees (joined by trace_id across
merged process exports) with each tree's critical-path TTFT breakdown;
`--waterfall TRACE_ID` draws one tree as an ASCII timeline.  `--prom`
attaches OpenMetrics exemplars (`# {trace_id="..."} value`) to histogram
buckets that have a sampled trace.

Exit status: 0 on a rendered report, 1 when the file is missing/empty,
2 on unparseable content.
"""

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple

DEFAULT_PATH = os.path.join("results", "obs.jsonl")


def load_records(path: str) -> List[dict]:
    """Parse every JSONL line; raises ValueError on a bad line (the bench
    post-run assertion leans on this being strict)."""
    records = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}") from e
            if not isinstance(rec, dict) or "kind" not in rec:
                raise ValueError(f"{path}:{i}: not an obs record: {line[:80]}")
            records.append(rec)
    return records


def merge_records(records: List[dict]) -> Tuple[List[dict], List[dict], dict]:
    """(final metric states, all spans, summary meta).  Metrics are keyed by
    (kind, name, labels) with last-wins — each snapshot is a full dump, so
    the last one is the run's final state.  Trace and exemplar records get
    their own channels (`meta["traces"]` / `meta["exemplars"]`): keying
    them like metrics would collapse every request's same-named lifecycle
    span into one."""
    metrics: Dict[tuple, dict] = {}
    spans: List[dict] = []
    traces: Dict[tuple, dict] = {}
    exemplars: Dict[tuple, dict] = {}
    n_snapshots = 0
    last_ts = ""
    seen_span_ids = set()
    for rec in records:
        kind = rec.get("kind")
        if kind == "meta":
            n_snapshots += 1
            last_ts = rec.get("ts_utc", last_ts)
        elif kind == "span":
            # spans re-export with every snapshot (append model): dedup by id
            sid = (rec.get("thread"), rec.get("span_id"))
            if sid not in seen_span_ids:
                seen_span_ids.add(sid)
                spans.append(rec)
        elif kind == "trace":
            # span ids are deterministic within a trace, so re-exported
            # snapshots dedup naturally on (trace_id, span_id)
            traces[(rec.get("trace_id"), rec.get("span_id"))] = rec
        elif kind == "exemplar":
            key = (rec.get("metric"), rec.get("le"))
            have = exemplars.get(key)
            if have is None or rec.get("value", 0) >= have.get("value", 0):
                exemplars[key] = rec
        else:
            key = (kind, rec.get("name"),
                   tuple(sorted((rec.get("labels") or {}).items())))
            metrics[key] = rec
    meta = {"snapshots": n_snapshots, "last_ts_utc": last_ts,
            "n_metrics": len(metrics), "n_spans": len(spans),
            "n_traces": len({t.get("trace_id") for t in traces.values()}),
            "traces": list(traces.values()),
            "exemplars": list(exemplars.values())}
    return list(metrics.values()), spans, meta


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _hist_line(rec: dict) -> str:
    parts = [f"count={rec['count']}", f"sum={rec['sum']:.6g}"]
    if rec["count"]:
        parts += [f"mean={rec['sum'] / rec['count']:.6g}",
                  f"min={rec['min']:.6g}", f"max={rec['max']:.6g}"]
    nonzero = [f"le{edge:g}:{cnt}" for edge, cnt in
               zip(rec.get("bucket_edges", []), rec.get("bucket_counts", []))
               if cnt]
    if rec.get("overflow"):
        nonzero.append(f"le+Inf:{rec['overflow']}")
    if nonzero:
        parts.append("buckets[" + " ".join(nonzero) + "]")
    return "  ".join(parts)


def render_text(metrics: List[dict], spans: List[dict], meta: dict,
                source: str) -> str:
    lines = [f"obs report — {source} "
             f"({meta['snapshots']} snapshot(s), last {meta['last_ts_utc']}, "
             f"{meta['n_metrics']} metrics, {meta['n_spans']} spans)"]
    by_kind: Dict[str, List[dict]] = {"counter": [], "gauge": [],
                                      "histogram": []}
    for rec in metrics:
        by_kind.setdefault(rec["kind"], []).append(rec)
    width = max([len(r["name"] + _fmt_labels(r.get("labels") or {}))
                 for r in metrics] + [20]) + 2
    for kind in ("counter", "gauge", "histogram"):
        recs = sorted(by_kind.get(kind, ()),
                      key=lambda r: (r["name"], sorted(
                          (r.get("labels") or {}).items())))
        if not recs:
            continue
        lines.append(f"{kind}s:")
        for rec in recs:
            tag = rec["name"] + _fmt_labels(rec.get("labels") or {})
            if kind == "histogram":
                lines.append(f"  {tag:<{width}} {_hist_line(rec)}")
            else:
                lines.append(f"  {tag:<{width}} {rec['value']:g}")
    if spans:
        lines.append("recent spans (newest last):")
        for rec in spans[-20:]:
            indent = "  " * (1 + int(rec.get("depth") or 0))
            lines.append(f"{indent}{rec['name']}  "
                         f"{rec['duration_s'] * 1e3:.3f} ms"
                         f"  [{rec.get('thread', '?')}]")
    return "\n".join(lines)


def render_prometheus(metrics: List[dict],
                      exemplars: List[dict] = ()) -> str:
    """Rebuild Prometheus text from merged final metric states.  Histogram
    buckets with a sampled trace gain an OpenMetrics exemplar suffix
    (`... # {trace_id="..."} value`) so a dashboard's p99 bucket can
    deep-link the actual waterfall (`obs --waterfall TRACE_ID`)."""
    from .registry import prom_name

    def plabels(labels, extra=""):
        parts = [f'{k}="{v}"' for k, v in sorted((labels or {}).items())]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    by_bucket = {(ex.get("metric"), ex.get("le")): ex for ex in exemplars}

    def exemplar(metric, le):
        ex = by_bucket.get((metric, le))
        if ex is None:
            return ""
        return f' # {{trace_id="{ex["trace_id"]}"}} {ex["value"]:g}'

    lines = []
    for rec in sorted(metrics, key=lambda r: (r["name"], sorted(
            (r.get("labels") or {}).items()))):
        name = prom_name(rec["name"])
        if rec["kind"] in ("counter", "gauge"):
            lines.append(f"# TYPE {name} {rec['kind']}")
            lines.append(f"{name}{plabels(rec.get('labels'))} "
                         f"{rec['value']:g}")
            continue
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for edge, cnt in zip(rec["bucket_edges"], rec["bucket_counts"]):
            cum += cnt
            lines.append(f"{name}_bucket"
                         f"{plabels(rec.get('labels'), 'le=%s' % json.dumps(str(edge)))} {cum}"
                         f"{exemplar(rec['name'], str(edge))}")
        cum += rec.get("overflow", 0)
        lines.append(f"{name}_bucket"
                     f"{plabels(rec.get('labels'), 'le=%s' % json.dumps('+Inf'))} {cum}"
                     f"{exemplar(rec['name'], '+Inf')}")
        lines.append(f"{name}_sum{plabels(rec.get('labels'))} {rec['sum']:g}")
        lines.append(f"{name}_count{plabels(rec.get('labels'))} "
                     f"{rec['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_trace_trees(trees: List[dict]) -> str:
    """One line per request tree: identity, join status, and the
    critical-path TTFT breakdown (phases sum to the TTFT by
    construction — `trace.ttft_breakdown`)."""
    from .trace import ttft_breakdown

    if not trees:
        return "obs traces: none recorded (tracing off, or nothing sampled)"
    lines = [f"obs traces — {len(trees)} tree(s)"]
    for tree in trees:
        procs = sorted({str(s.get("process_index"))
                        for s in tree["spans"] if "process_index" in s})
        status = "complete" if tree["complete"] else "PARTIAL"
        if tree["truncated"]:
            status += "+truncated"
        head = (f"  {tree['trace_id']}  [{status}]  "
                f"{len(tree['spans'])} span(s)")
        if procs:
            head += f"  procs[{','.join(procs)}]"
        lines.append(head)
        bd = ttft_breakdown(tree["spans"])
        if bd is not None:
            phases = "  ".join(f"{k}={v * 1e3:.3f}ms"
                               for k, v in bd["phases"].items())
            lines.append(f"    ttft {bd['ttft_s'] * 1e3:.3f}ms "
                         f"({bd['clock']} clock): {phases}")
    return "\n".join(lines)


def render_waterfall(tree: dict) -> str:
    """ASCII waterfall of one trace tree: every span as a positioned bar
    on the request's own timeline (t=0 at the earliest span start)."""
    spans = sorted(tree["spans"], key=lambda s: (s["start_s"], s["name"]))
    t0 = spans[0]["start_s"]
    t1 = max(s["start_s"] + s["duration_s"] for s in spans)
    total = max(t1 - t0, 1e-9)
    width = 48
    name_w = max(len(s["name"]) for s in spans) + 2
    status = "complete" if tree["complete"] else "PARTIAL"
    if tree["truncated"]:
        status += "+truncated"
    lines = [f"waterfall {tree['trace_id']}  [{status}]  "
             f"span {total * 1e3:.3f}ms"]
    for s in spans:
        lo = int((s["start_s"] - t0) / total * width)
        hi = int((s["start_s"] + s["duration_s"] - t0) / total * width)
        bar = " " * lo + ("|" if hi <= lo else "#" * (hi - lo))
        proc = (f" p{s['process_index']}"
                if "process_index" in s else "")
        lines.append(f"  {s['name']:<{name_w}}[{bar:<{width}}] "
                     f"+{(s['start_s'] - t0) * 1e3:.3f}ms "
                     f"{s['duration_s'] * 1e3:.3f}ms{proc}")
    return "\n".join(lines)


def _render_traces(meta: dict, args) -> int:
    from .aggregate import build_trace_trees

    trees = build_trace_trees(meta.get("traces", []),
                              meta.get("truncated_processes", ()))
    if args.waterfall:
        for tree in trees:
            if tree["trace_id"] == args.waterfall:
                print(render_waterfall(tree))
                return 0
        print(f"obs: no trace tree {args.waterfall!r} "
              f"({len(trees)} tree(s) present)", file=sys.stderr)
        return 1
    if args.as_json:
        from .trace import ttft_breakdown

        print(json.dumps([dict(t, breakdown=ttft_breakdown(t["spans"]))
                          for t in trees], indent=1))
    else:
        print(render_trace_trees(trees))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m burst_attn_tpu.obs",
        description="render a report from an obs JSONL export")
    ap.add_argument("--file", default=DEFAULT_PATH,
                    help=f"JSONL export to read (default: {DEFAULT_PATH})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit machine-readable JSON")
    ap.add_argument("--prom", action="store_true",
                    help="emit Prometheus text exposition format")
    ap.add_argument("--merge", action="append", metavar="GLOB", default=[],
                    help="merge per-process exports matching this glob into "
                         "one job-level report (repeatable)")
    ap.add_argument("--by-process", action="store_true",
                    help="with --merge: keep every metric child per process "
                         "(process_index label) instead of folding")
    ap.add_argument("--trace", action="store_true",
                    help="render per-request trace trees with their "
                         "critical-path TTFT breakdown")
    ap.add_argument("--waterfall", metavar="TRACE_ID",
                    help="ASCII waterfall for one trace tree (implies "
                         "--trace)")
    args = ap.parse_args(argv)

    if args.merge:
        from .aggregate import merge_files, resolve_files

        try:
            metrics, spans, meta = merge_files(args.merge,
                                               by_process=args.by_process)
        except FileNotFoundError as e:
            print(f"obs: {e}", file=sys.stderr)
            return 1
        except ValueError as e:
            print(f"obs: {e}", file=sys.stderr)
            return 2
        source = (f"merge of {meta['processes']} process export(s) "
                  f"[{', '.join(resolve_files(args.merge))}]")
        if args.trace or args.waterfall:
            return _render_traces(meta, args)
        if args.prom:
            sys.stdout.write(render_prometheus(metrics,
                                               meta.get("exemplars", ())))
        elif args.as_json:
            print(json.dumps({"source": source, "meta": meta,
                              "metrics": metrics, "spans": spans}, indent=1))
        else:
            print(render_text(metrics, spans, meta, source))
        return 0

    if not os.path.exists(args.file):
        print(f"obs: no export at {args.file} (run bench.py or call "
              "obs.export_jsonl first)", file=sys.stderr)
        return 1
    try:
        records = load_records(args.file)
    except ValueError as e:
        print(f"obs: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"obs: {args.file} is empty", file=sys.stderr)
        return 1
    metrics, spans, meta = merge_records(records)
    if args.trace or args.waterfall:
        return _render_traces(meta, args)
    if args.prom:
        sys.stdout.write(render_prometheus(metrics,
                                           meta.get("exemplars", ())))
    elif args.as_json:
        print(json.dumps({"source": args.file, "meta": meta,
                          "metrics": metrics, "spans": spans}, indent=1))
    else:
        print(render_text(metrics, spans, meta, args.file))
    return 0


if __name__ == "__main__":
    sys.exit(main())
