"""Distributed request tracing: per-request causal timelines.

A `TraceContext` is the identity a request carries from submission to
retirement: a `trace_id` shared by every span in the request's tree, the
recording site's `span_id`, and a `parent_id` linking the span upward.
The context crosses process boundaries as a compact wire form
(`to_wire()` / `from_wire()`) riding as an OPTIONAL trailing element on
fleet messages — absent entirely when tracing is off, so an untraced
run's frames encode byte-identical to a build without this module.

Recording sits under the same JIT-safety contract as spans.py: every
record call is a guarded no-op while the calling thread is inside a jax
trace, and burstlint's `obs-jit-safe` rule AST-proves no trace-record
call is reachable from a jit-marked function in the first place.
Tracing is OFF by default; every instrumentation site checks `enabled()`
before doing any work (the serve tick's jaxpr is untouched either way —
only host clocks are read).

Clocks.  Real engines record absolute `time.perf_counter()` timestamps:
CLOCK_MONOTONIC is system-wide on Linux, so spans recorded by the
router, prefill and decode processes of a same-host fleet share one
timeline and merge into a single causal tree (`obs --merge` joins by
trace_id).  The fleet simulator records its virtual event clock with
`clock="virtual"` — same record schema, so a policy's simulated
waterfall diffs directly against a real `--fleet` run.

Sampling is tail-based and bounded.  All spans land in a bounded ring
(MAX_TRACE_RECORDS); at export time a full tree is kept only when its
request's TTFT ranks in the top TAIL_KEEP observed so far (the tail the
p99 argues about) or its trace_id head-samples in deterministically
(1/HEAD_SAMPLE_N, hash-based — no RNG state).  `note_ttft` also pins the
worst trace per latency bucket as an OpenMetrics exemplar, so
`obs --prom` can deep-link `serve_ttft_s` buckets to actual waterfalls.

`ttft_breakdown` is the critical-path analyzer: it decomposes a tree's
TTFT into contiguous phase contributions (uncovered time is an explicit
"gap" phase), so the phases sum to the TTFT by construction.
"""

import collections
import itertools
import os
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from .registry import LATENCY_BUCKETS_S, default_registry
from .spans import _tracing

# bounded buffers: a long-serving process cannot grow without limit
MAX_TRACE_RECORDS = 8192
TAIL_KEEP = 64          # full trees kept for the TAIL_KEEP worst TTFTs
HEAD_SAMPLE_N = 8       # plus a deterministic 1/N head sample of the rest

_records = collections.deque(maxlen=MAX_TRACE_RECORDS)
_ttfts: Dict[str, float] = {}          # trace_id -> noted TTFT (bounded below)
_exemplars: Dict[tuple, dict] = {}     # (metric, le) -> worst exemplar record
_lock = threading.Lock()
_seq = itertools.count(1)
_enabled = False


def enable(on: bool = True) -> None:
    """Flip the module-wide tracing switch (default OFF — every
    instrumentation site checks `enabled()` first, so the feature costs
    nothing while this is False)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


@dataclass(frozen=True)
class TraceContext:
    """The identity a request carries: which tree (`trace_id`), which
    span records made under this context hang from (`span_id`), and what
    that span's own parent is (`parent_id`, None at the root)."""

    trace_id: str
    span_id: str = "request"
    parent_id: Optional[str] = None
    clock: str = "wall"

    def child(self, span_id: str) -> "TraceContext":
        """Context for recording under the span named `span_id`."""
        return TraceContext(self.trace_id, span_id, self.span_id, self.clock)

    def to_wire(self) -> List[str]:
        """Compact wire form for transport payloads (msgpack/JSON-able)."""
        return [self.trace_id, self.span_id]

    @staticmethod
    def from_wire(wire) -> Optional["TraceContext"]:
        """Inverse of `to_wire`; None on a missing/garbled field (a peer
        without tracing simply never attaches one)."""
        if not wire or not isinstance(wire, (list, tuple)) or len(wire) < 2:
            return None
        try:
            return TraceContext(str(wire[0]), str(wire[1]))
        except Exception:  # noqa: BLE001 — never let telemetry break serving
            return None


def start_request(rid, prefix: str = "serve",
                  clock: str = "wall") -> Optional[TraceContext]:
    """Root context for a newly submitted request, or None when tracing
    is off (callers keep a single `if tc is not None` guard).  The
    trace_id embeds the pid and a process-local sequence number so
    concurrent engines and fleet processes never collide."""
    if not _enabled:
        return None
    return TraceContext(f"{prefix}-{os.getpid()}-r{rid}-{next(_seq)}",
                        "request", None, clock)


def record_span(tc: Optional[TraceContext], name: str, start_s: float,
                end_s: float, root: bool = False, **attrs) -> None:
    """Record one completed span of `tc`'s tree with EXPLICIT times (the
    caller read the clock, or owns a virtual one — the simulator records
    event times that were never wall instants).  `root=True` records the
    context's own span (parent `tc.parent_id`); otherwise the span is a
    child of `tc.span_id` with a deterministic name-based span_id —
    lifecycle phase names are unique within a request's tree, so ids
    need no coordination across processes.

    No-op when tracing is off, `tc` is None, or the calling thread is
    inside a jax trace (same degrade as spans.span)."""
    if not _enabled or tc is None or _tracing():
        return
    rec = {"kind": "trace", "trace_id": tc.trace_id,
           "span_id": tc.span_id if root else name,
           "parent_id": tc.parent_id if root else tc.span_id,
           "name": name, "start_s": round(float(start_s), 9),
           "duration_s": round(max(0.0, float(end_s) - float(start_s)), 9),
           "clock": tc.clock, "attrs": attrs}
    with _lock:
        _records.append(rec)


def marker(tc: Optional[TraceContext], name: str, t_s: float,
           **attrs) -> None:
    """Zero-duration event span (e.g. the first-token instant)."""
    record_span(tc, name, t_s, t_s, **attrs)


class _SpanCtx:
    """Handle from `span()`: wall-clocked child span as a with-block."""

    __slots__ = ("_tc", "_name", "_attrs", "_t0")

    def __init__(self, tc, name, attrs):
        self._tc, self._name, self._attrs = tc, name, attrs
        self._t0 = None

    def __enter__(self):
        if _enabled and self._tc is not None and not _tracing():
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            record_span(self._tc, self._name, self._t0,
                        time.perf_counter(), **self._attrs)
        return False


def span(tc: Optional[TraceContext], name: str, **attrs) -> _SpanCtx:
    """`with trace.span(tc, "fleet.prefill"): ...` — wall-clock child
    span; a no-op context manager when tracing is off or tc is None."""
    return _SpanCtx(tc, name, attrs)


def note_ttft(tc_or_id, ttft_s: float, metric: str = "serve.ttft_s") -> None:
    """Register a request's measured TTFT with the sampler: ranks the
    trace for tail retention and pins it as the exemplar of `metric`'s
    latency bucket when it is the worst seen there (last-wins on ties —
    fresher waterfalls beat stale ones)."""
    if not _enabled or tc_or_id is None or _tracing():
        return
    trace_id = getattr(tc_or_id, "trace_id", tc_or_id)
    ttft_s = float(ttft_s)
    edges = LATENCY_BUCKETS_S
    m = default_registry()._metrics.get(metric)  # no get-or-create
    if m is not None and getattr(m, "buckets", None):
        edges = m.buckets
    le = next((str(e) for e in edges if ttft_s <= e), "+Inf")
    with _lock:
        _ttfts[str(trace_id)] = ttft_s
        if len(_ttfts) > 4 * TAIL_KEEP:
            # bound the rank table: drop the fastest half, they can never
            # re-enter the kept tail
            for tid in sorted(_ttfts, key=_ttfts.get)[:2 * TAIL_KEEP]:
                del _ttfts[tid]
        have = _exemplars.get((metric, le))
        if have is None or ttft_s >= have["value"]:
            _exemplars[(metric, le)] = {"kind": "exemplar", "metric": metric,
                                        "le": le, "trace_id": str(trace_id),
                                        "value": ttft_s}


def publish_breakdown(phases: Dict[str, float],
                      metric: str = "serve.ttft_breakdown") -> None:
    """Feed a request's phase decomposition into the registry histogram
    `serve.ttft_breakdown{phase=...}` (host-side aggregate view of what
    the per-trace analyzer computes exactly)."""
    if _tracing():
        return
    hist = default_registry().histogram(metric)
    for phase, seconds in phases.items():
        hist.observe(max(0.0, float(seconds)), phase=phase)


def _kept_trace_ids() -> set:
    """Sampling policy at export time: the TAIL_KEEP worst TTFTs plus the
    deterministic head sample.  Traces with no noted TTFT yet (still in
    flight, or recorded by a stage that never sees first-token) are kept —
    dropping them would tear cross-process trees whose TTFT was noted by
    a DIFFERENT process (the router notes; workers just record spans)."""
    with _lock:
        tail = set(sorted(_ttfts, key=_ttfts.get, reverse=True)[:TAIL_KEEP])
        noted = set(_ttfts)
        seen = {r["trace_id"] for r in _records}
    head = {tid for tid in seen
            if zlib.crc32(tid.encode()) % HEAD_SAMPLE_N == 0}
    return tail | head | (seen - noted)


def trace_records() -> List[dict]:
    """Sampled trace records for export (joins spans.span_records() in
    `obs.export_jsonl`'s extra_records)."""
    if not _records:
        return []
    keep = _kept_trace_ids()
    with _lock:
        return [r for r in _records if r["trace_id"] in keep]


def exemplar_records() -> List[dict]:
    with _lock:
        return list(_exemplars.values())


def reset_traces() -> None:
    """Drop all trace state and disable tracing (tests)."""
    global _enabled
    with _lock:
        _records.clear()
        _ttfts.clear()
        _exemplars.clear()
    _enabled = False


def ttft_breakdown(spans: Sequence[dict]) -> Optional[dict]:
    """Critical-path decomposition of one trace tree's TTFT.

    `spans` is the tree's trace records (any order).  The root span
    (parent_id None) anchors t=0; the first-token instant is the end of
    the earliest span whose name ends in "first_token" (falling back to
    the root's end).  Each direct child of the root contributes its
    clipped, non-overlapping share of [root start, first token] walking
    left to right; uncovered time is the explicit "gap" phase — so the
    phases ALWAYS sum to the returned ttft_s exactly (the acceptance
    bar's "within 1%" is float-noise tolerance, not lost time).  Returns
    {"ttft_s", "phases", "clock"} or None when the tree has no root."""
    roots = [s for s in spans if s.get("parent_id") is None]
    if not roots:
        return None
    root = min(roots, key=lambda s: s["start_s"])
    t0 = root["start_s"]
    firsts = [s for s in spans if s["name"].endswith("first_token")]
    if firsts:
        ft = min(firsts, key=lambda s: s["start_s"])
        t_first = ft["start_s"] + ft["duration_s"]
    else:
        t_first = t0 + root["duration_s"]
    children = sorted(
        (s for s in spans
         if s.get("parent_id") == root["span_id"]
         and not s["name"].endswith("first_token")),
        key=lambda s: s["start_s"])
    phases: Dict[str, float] = {}
    cursor, gap = t0, 0.0
    for s in children:
        lo = max(s["start_s"], cursor)
        hi = min(s["start_s"] + s["duration_s"], t_first)
        if hi <= lo:
            continue
        gap += lo - cursor
        key = s["name"].rsplit(".", 1)[-1]
        phases[key] = phases.get(key, 0.0) + (hi - lo)
        cursor = hi
    gap += max(0.0, t_first - cursor)
    phases["gap"] = gap
    return {"ttft_s": t_first - t0, "phases": phases,
            "clock": root.get("clock", "wall")}
