"""Metrics registry: counters, gauges, fixed-bucket histograms.

Host-side only, by design.  Every update is a lock-guarded float op on the
Python heap — safe to call from the serving loop, the training loop, loader
threads, and trace-time dispatch code, and cheap enough (sub-microsecond)
that instrumenting a hot host path costs nothing against a device step.
Nothing here may ever touch a device or a jax transform: keeping the
registry dumb is what makes the `obs-jit-safe` burstlint contract provable
(no registry call can smuggle a host callback into a compiled program).

Aggregation model: one `Registry` per process (the module default is what
the instrumented subsystems share); multi-process runs export per-process
JSONL files and the CLI merges them.  Counters and gauges fan out by label
set (sorted key/value tuples), like Prometheus children.

Counter semantics note for trace-time instrumentation (parallel/burst.py):
counters incremented while jax is TRACING advance once per compiled
program, not once per executed step — exactly the right unit for dispatch
decisions ("how many programs took the fused path"), and the docs
(docs/observability.md) call out which catalog entries are per-trace.

Exporters:
  * `to_prometheus()`  — Prometheus text exposition format (counters,
    gauges, cumulative histogram buckets with `le` labels).
  * `export_jsonl(path)` — append a full snapshot, one JSON object per
    metric child plus a `meta` header, flushed AND fsynced so a killed run
    (driver timeout, SIGKILL) keeps everything exported before the kill.
"""

import bisect
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Default histogram buckets: latency-shaped, 100 us .. 60 s.  Fixed at
# construction — observations above the last edge land in the implicit
# +Inf overflow bucket, never resize anything.
LATENCY_BUCKETS_S = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _lkey(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _ldict(key: LabelKey) -> Dict[str, str]:
    return dict(key)


class _Metric:
    kind = "abstract"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def _records(self) -> List[dict]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotone float counter with optional labels: `c.inc(2, path="fused")`."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._vals: Dict[LabelKey, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative inc {n}")
        key = _lkey(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n

    def get(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_lkey(labels), 0.0)

    def total(self) -> float:
        """Sum over every label child."""
        with self._lock:
            return sum(self._vals.values())

    def _records(self):
        with self._lock:
            return [{"kind": self.kind, "name": self.name,
                     "labels": _ldict(k), "value": v}
                    for k, v in sorted(self._vals.items())]


class Gauge(_Metric):
    """Last-write-wins float gauge (queue depth, occupancy, rates)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._vals: Dict[LabelKey, float] = {}

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._vals[_lkey(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels) -> None:
        key = _lkey(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)

    def get(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_lkey(labels), 0.0)

    def _records(self):
        with self._lock:
            return [{"kind": self.kind, "name": self.name,
                     "labels": _ldict(k), "value": v}
                    for k, v in sorted(self._vals.items())]


class _HistChild:
    __slots__ = ("counts", "overflow", "sum", "count", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.overflow = 0
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")


class Histogram(_Metric):
    """Fixed-bucket histogram.  Bucket edges are upper bounds with `<=`
    (Prometheus `le`) semantics: a value exactly on an edge counts in that
    edge's bucket; values above the last edge go to the +Inf overflow."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help)
        edges = tuple(buckets) if buckets is not None else LATENCY_BUCKETS_S
        if not edges or list(edges) != sorted(set(edges)):
            raise ValueError(
                f"histogram {name}: buckets must be strictly increasing, "
                f"got {edges}")
        self.buckets = tuple(float(e) for e in edges)
        self._children: Dict[LabelKey, _HistChild] = {}

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        key = _lkey(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _HistChild(len(self.buckets))
            # first edge >= v (le semantics); past the end -> overflow
            i = bisect.bisect_left(self.buckets, v)
            if i < len(self.buckets):
                child.counts[i] += 1
            else:
                child.overflow += 1
            child.sum += v
            child.count += 1
            child.min = min(child.min, v)
            child.max = max(child.max, v)

    def get(self, **labels) -> dict:
        """Snapshot of one child: count/sum/min/max + per-bucket counts
        (NON-cumulative, keyed by upper edge; "+Inf" is the overflow)."""
        with self._lock:
            child = self._children.get(_lkey(labels))
            if child is None:
                return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                        "buckets": {}}
            buckets = {repr(e): c for e, c in zip(self.buckets, child.counts)
                       if c}
            if child.overflow:
                buckets["+Inf"] = child.overflow
            return {"count": child.count, "sum": child.sum,
                    "min": child.min, "max": child.max, "buckets": buckets}

    def _records(self):
        with self._lock:
            out = []
            for key, child in sorted(self._children.items()):
                out.append({
                    "kind": self.kind, "name": self.name,
                    "labels": _ldict(key),
                    "count": child.count, "sum": child.sum,
                    "min": child.min, "max": child.max,
                    "bucket_edges": list(self.buckets),
                    "bucket_counts": list(child.counts),
                    "overflow": child.overflow,
                })
            return out


_PROM_SAFE = str.maketrans({".": "_", "-": "_", "/": "_"})


def prom_name(name: str) -> str:
    """`serve.ttft_s` -> `burst_serve_ttft_s` (exposition-format safe)."""
    return "burst_" + name.translate(_PROM_SAFE)


def _prom_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Registry:
    """Named metrics, get-or-create.  Re-requesting a name returns the same
    object; a kind mismatch (histogram where a counter lives) raises —
    silent shadowing would split a metric across two objects."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def reset(self) -> None:
        """Drop every metric (tests; a long-lived server never calls this)."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> List[dict]:
        """All metric children as plain JSON-able dicts."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: List[dict] = []
        for m in metrics:
            out += m._records()
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (cumulative histogram buckets)."""
        lines: List[str] = []
        for rec in self.snapshot():
            name = prom_name(rec["name"])
            if rec["kind"] in ("counter", "gauge"):
                lines.append(f"# TYPE {name} {rec['kind']}")
                lines.append(
                    f"{name}{_prom_labels(rec['labels'])} {rec['value']:g}")
                continue
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for edge, cnt in zip(rec["bucket_edges"], rec["bucket_counts"]):
                cum += cnt
                le = 'le="%g"' % edge
                lines.append(
                    f"{name}_bucket{_prom_labels(rec['labels'], le)} {cum}")
            cum += rec["overflow"]
            inf = 'le="+Inf"'
            lines.append(
                f"{name}_bucket{_prom_labels(rec['labels'], inf)} {cum}")
            lines.append(f"{name}_sum{_prom_labels(rec['labels'])}"
                         f" {rec['sum']:g}")
            lines.append(f"{name}_count{_prom_labels(rec['labels'])}"
                         f" {rec['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export_jsonl(self, path: str, extra_records: Sequence[dict] = (),
                     process_index: Optional[int] = None) -> str:
        """Append a full snapshot to `path` (one JSON object per line,
        `meta` header first), fsynced before returning — a run killed right
        after export still leaves a complete, parseable file.

        `process_index`: multi-host process label written into the meta
        header (the CLI `--merge` reader keys per-process states on it);
        the registry itself stays jax-free — obs.export_jsonl fills it in."""
        records = self.snapshot()
        meta = {
            "kind": "meta",
            "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            "n_records": len(records) + len(extra_records),
        }
        if process_index is not None:
            meta["process_index"] = int(process_index)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(meta) + "\n")
            for rec in records:
                f.write(json.dumps(rec) + "\n")
            for rec in extra_records:
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return path


# the per-process default registry every instrumented subsystem shares
_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT
