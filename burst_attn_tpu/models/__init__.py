from .transformer import ModelConfig, init_params, forward, forward_with_aux, param_specs
from .train import (TrainConfig, make_mesh, init_train_state, train_step,
                    loss_fn, packed_fields, probe_model_tri_bwd)
from .decode import Cache, forward_cached, generate, init_cache, prefill, sample_logits
from .dist_decode import DistCache, dist_generate, dist_prefill
from .paged_decode import (
    PagePool, PagedState, PrefixCache, ensure_capacity, init_paged_state,
    paged_decode_step, paged_multi_step, paged_prefill,
    provision_capacity, retire_slot, rollback_tokens,
)
from .pipeline_lm import stack_layers, unstack_layers
from .serve import ServeEngine
from .speculative import SpecStats, speculative_generate

__all__ = [
    "sample_logits",
    "stack_layers",
    "unstack_layers",
    "ModelConfig",
    "init_params",
    "forward",
    "forward_with_aux",
    "param_specs",
    "TrainConfig",
    "make_mesh",
    "init_train_state",
    "train_step",
    "packed_fields",
    "probe_model_tri_bwd",
    "loss_fn",
    "Cache",
    "forward_cached",
    "generate",
    "init_cache",
    "prefill",
    "DistCache",
    "dist_generate",
    "dist_prefill",
    "PagePool",
    "PagedState",
    "PrefixCache",
    "ensure_capacity",
    "init_paged_state",
    "paged_decode_step",
    "paged_prefill",
    "paged_multi_step",
    "provision_capacity",
    "rollback_tokens",
    "retire_slot",
    "ServeEngine",
    "SpecStats",
    "speculative_generate",
]
