"""Autoregressive inference for the flagship LM: KV-cache prefill + decode.

The reference is a training-time op library with no inference story; a
complete framework needs one.  TPU-first design choices:

  * The KV cache is a pair of preallocated [B, Nkv, max_seq, D] buffers per
    layer (static shapes — no reallocation, no dynamic shapes under jit);
    `lax.dynamic_update_slice` writes the new tokens' K/V at the current
    length.
  * One function serves prefill (T = prompt length) and decode (T = 1): the
    causal predicate against the cache is `col <= cache_len + row`, so a
    whole prompt is absorbed in one fused pass rather than token by token.
  * `generate` runs the decode loop inside ONE jit via `lax.scan` — no
    per-token dispatch overhead (which dominates single-token steps on TPU).
  * Tokens stay in natural order — ring layouts (parallel/layouts.py) are a
    training-time concern; decode shards over batch (dp) and heads (tp).
"""

from functools import partial
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import ModelConfig, _attn_out, _mlp, _qkv_proj, _rms_norm


class LayerCache(NamedTuple):
    k: jax.Array  # [B, Nkv, max_seq, D]
    v: jax.Array  # [B, Nkv, max_seq, D]


class Cache(NamedTuple):
    layers: Tuple[LayerCache, ...]
    length: jax.Array  # scalar int32: number of valid cache positions


def init_cache(cfg: ModelConfig, batch: int, max_seq: int) -> Cache:
    shape = (batch, cfg.n_kv_heads, max_seq, cfg.d_head)
    layers = tuple(
        LayerCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
        for _ in range(cfg.n_layers)
    )
    return Cache(layers, jnp.int32(0))


def _flash_prompt_attention(q, k, v, use_flash=None, window=None):
    """Causal self-attention over a fresh prompt — O(T) memory via the flash
    tile instead of the [T, max_seq] score matrix (which makes long-context
    prefill impossible: 32 heads x 32K x 32K f32 scores is ~137 GB).

    use_flash: None = auto (flash kernel on TPU, jnp tile elsewhere);
    override for tests (the flash branch runs in interpret mode off-TPU).
    """
    t = q.shape[2]
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        from ..ops.pallas_flash import flash_attention

        # pad to the kernel's tile granularity; CAUSAL masking keeps the
        # zero-padded tail out of every real row's receptive field (col <=
        # row: a padded column j >= t is visible only to padded rows i >= j)
        pad = (-t) % 128
        if pad:
            cfgp = [(0, 0), (0, 0), (0, pad), (0, 0)]
            q, k, v = (jnp.pad(a, cfgp) for a in (q, k, v))
        o = flash_attention(q, k, v, None, True, window=window)
        return o[:, :, :t] if pad else o
    from ..ops.tile import single_device_attention

    # GQA: the jnp tile wants equal heads; repeat K/V (CPU path, small)
    group = q.shape[1] // k.shape[1]
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    return single_device_attention(q, k, v, causal=True, window=window)


def _cached_attention(p, x, positions, lc: LayerCache, cache_len, cfg: ModelConfig,
                      fresh: bool = False):
    """Attend the T new tokens against [cache .. cache+T); returns (out, new
    LayerCache).  positions: [B, T] global positions of the new tokens.
    `fresh` (static) marks an empty cache — the prompt attends only to
    itself, so the flash path applies and the cache buffers are write-only.
    """
    b, t, _ = x.shape
    q, k, v = _qkv_proj(p, x, positions, cfg)

    ck = lax.dynamic_update_slice(lc.k, k.astype(lc.k.dtype), (0, 0, cache_len, 0))
    cv = lax.dynamic_update_slice(lc.v, v.astype(lc.v.dtype), (0, 0, cache_len, 0))

    if fresh:
        o = _flash_prompt_attention(q, k.astype(lc.k.dtype),
                                    v.astype(lc.v.dtype), window=cfg.window)
    else:
        # GQA via a grouped query axis — never materialize a repeated cache
        # (at decode the [B, Nkv, max_seq, D] buffers dominate memory traffic)
        group = cfg.n_heads // cfg.n_kv_heads
        qg = q.reshape(q.shape[0], cfg.n_kv_heads, group, t, cfg.d_head)
        s = jnp.einsum(
            "bngih,bnjh->bngij", qg, ck, preferred_element_type=jnp.float32
        ) * (cfg.d_head**-0.5)
        rows = jnp.arange(t, dtype=jnp.int32)[:, None]
        cols = jnp.arange(ck.shape[2], dtype=jnp.int32)[None, :]
        visible = cols <= cache_len + rows
        if cfg.window is not None:
            # sliding window carries into decode: a query at global position
            # cache_len + row sees only its last `window` positions
            visible = visible & (cols > cache_len + rows - cfg.window)
        s = jnp.where(visible, s, float("-inf"))
        prob = jax.nn.softmax(s, axis=-1).astype(cv.dtype)
        o = jnp.einsum("bngij,bnjh->bngih", prob, cv)
        o = o.reshape(q.shape[0], cfg.n_heads, t, cfg.d_head)
    out = _attn_out(p, o)
    return out, LayerCache(ck, cv)


def forward_cached(params, tokens, positions, cache: Cache, cfg: ModelConfig):
    """One cached forward pass over T new tokens.

    tokens, positions: [B, T] int32 (natural order).  Returns (fp32 logits
    [B, T, vocab], updated Cache with length += T).
    """
    return _forward_cached_impl(params, tokens, positions, cache, cfg, fresh=False)


def _forward_cached_impl(params, tokens, positions, cache: Cache,
                         cfg: ModelConfig, *, fresh: bool):
    """`fresh` (static) asserts the cache is EMPTY, enabling the O(T)-memory
    flash prefill path that ignores cache contents — which is why it is not
    on the public signature: with a non-empty cache it would silently drop
    all cached context.  `prefill` is the only fresh caller."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    new_layers = []
    for p, lc in zip(params["layers"], cache.layers):
        attn_out, lc = _cached_attention(p, x, positions, lc, cache.length, cfg,
                                         fresh=fresh)
        x = x + attn_out
        m, _ = _mlp(p, x, cfg, inference=True)  # drop-free capacity; aux unused
        x = x + m
        new_layers.append(lc)
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "bsd,vd->bsv", x, params["lm_head"], preferred_element_type=jnp.float32
    )
    return logits, Cache(tuple(new_layers), cache.length + tokens.shape[1])


def prefill(params, tokens, cfg: ModelConfig, max_seq: int):
    """Absorb a [B, T] prompt in one pass.  Returns (logits, cache)."""
    b, t = tokens.shape
    if t > max_seq:
        raise ValueError(f"prompt length {t} exceeds max_seq {max_seq}")
    cache = init_cache(cfg, b, max_seq)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))
    return _forward_cached_impl(params, tokens, positions, cache, cfg, fresh=True)


def sample_logits(logits, key, *, temperature: float = 0.0, top_k=None,
                  top_p=None, nan_sentinel: bool = False):
    """[B, V] logits -> [B] sampled token ids.

    temperature == 0 is greedy (top_k/top_p ignored).  Otherwise softmax
    sampling at `temperature`, after optional top-k truncation and/or
    top-p (nucleus) truncation — the kept set is the smallest prefix of
    the sorted distribution whose probability reaches top_p.  All
    selection is done by masking to -inf so the op stays one fused
    [B, V]-wide program (no gathers of dynamic width).

    nan_sentinel=True makes rows containing NaN sample -1 instead of a
    silent argmax-of-NaN 0: the paged decode steps poison a slot's logits
    with NaN when a live slot was stepped without capacity
    (models/paged_decode.py loud-failure contract), and the sentinel
    survives the host fetch so ServeEngine can raise without transferring
    the [B, V] logits a second time.  It is OPT-IN because callers that
    feed the sample straight back as the next input token (generate()'s
    scan, dist_decode) would embed-gather index -1 instead."""
    bad = jnp.any(jnp.isnan(logits), axis=-1) if nan_sentinel else None
    if temperature <= 0.0:
        tok = jnp.argmax(logits, axis=-1)
        return tok if bad is None else jnp.where(bad, -1, tok)
    if bad is not None:
        # keep categorical's input finite for the poisoned rows
        logits = jnp.where(bad[:, None], 0.0, logits)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        k_eff = min(int(top_k), logits.shape[-1])  # top_k > vocab = keep all
        kth = jnp.sort(logits, axis=-1)[:, -k_eff][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum_before = jnp.cumsum(probs, axis=-1) - probs
        keep = cum_before < top_p  # always keeps the argmax
        thresh = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    tok = jax.random.categorical(key, logits, axis=-1)
    return tok if bad is None else jnp.where(bad, -1, tok)


@partial(jax.jit, static_argnames=("cfg", "steps", "max_seq", "temperature",
                                   "top_k", "top_p"))
def generate(params, prompt, cfg: ModelConfig, *, steps: int, max_seq: int,
             temperature: float = 0.0, top_k=None, top_p=None, rng=None):
    """Greedy (temperature=0) or sampled (temperature/top_k/top_p)
    generation.

    prompt: [B, T] int32.  Returns [B, steps] generated tokens.  The decode
    loop is a lax.scan — one compiled program, no per-token dispatch.
    """
    if prompt.shape[1] + steps > max_seq:
        raise ValueError("prompt + steps exceeds max_seq")
    logits, cache = prefill(params, prompt, cfg, max_seq)
    b = prompt.shape[0]
    rng = jax.random.PRNGKey(0) if rng is None else rng
    rng, first_key = jax.random.split(rng)

    def pick(logits_last, key):
        return sample_logits(logits_last, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    first = pick(logits[:, -1], first_key)

    def body(carry, key):
        token, cache = carry
        positions = jnp.broadcast_to(cache.length[None, None], (b, 1)).astype(jnp.int32)
        logits, cache = forward_cached(
            params, token[:, None], positions, cache, cfg
        )
        nxt = pick(logits[:, -1], key)
        return (nxt, cache), token

    keys = jax.random.split(rng, steps)
    (_, _), toks = lax.scan(body, (first, cache), keys[:steps])
    return jnp.moveaxis(toks, 0, 1)  # [B, steps]
