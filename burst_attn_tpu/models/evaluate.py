"""Held-out evaluation: mean next-token cross entropy / perplexity over a
token file, using the same sharded forward as training (no optimizer)."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from .train import batch_from_host
from .transformer import ModelConfig
from ..data import DataLoader


def make_eval_step(cfg: ModelConfig, mesh):
    """Jitted (params, batch) -> (nll sum, valid-token count): the caller
    aggregates sum/count across batches so the reported eval_loss is the
    same token-weighted objective as the train loss (a per-batch mean of
    means would overweight sparse batches — packed crops mask unevenly)."""
    from .train import _loss_parts

    def step(params, batch):
        nll_sum, _ = _loss_parts(params, batch["tokens"], batch["positions"],
                                 batch["labels"], cfg, mesh,
                                 segment_ids=batch.get("segment_ids"))
        return nll_sum, jnp.sum(batch["labels"] >= 0)

    return jax.jit(step)


class Evaluator:
    """Reusable held-out eval: the jitted step is compiled once and the
    (sequential, unshuffled) loader stays open across rounds — a long run's
    periodic evals pay execution cost only, not an XLA recompile plus a
    loader setup per round.  Each __call__ rewinds to the stream start so
    every eval sees the same batches."""

    def __init__(self, cfg: ModelConfig, mesh, data_path, *, batch: int,
                 seq_len: int, max_batches: int = 32, packed_eos_id=None):
        self._step = make_eval_step(cfg, mesh)
        self._cfg, self._mesh = cfg, mesh
        # packed training must be EVALUATED packed too, or eval_loss
        # measures a different objective (cross-document attention,
        # unmasked boundaries) than the train loss
        self._packed_eos_id = packed_eos_id
        self._loader = DataLoader(
            data_path, batch, seq_len,
            shard_id=jax.process_index(), num_shards=jax.process_count(),
            shuffle=False,
        )
        self._n = min(max_batches,
                      max(1, self._loader.windows_per_epoch // batch))

    def __call__(self, params) -> dict:
        self._loader.seek(0)
        nll_total, n_total = 0.0, 0
        for _ in range(self._n):
            x, y = self._loader.next()
            nll, n = self._step(params, batch_from_host(
                x, y, self._cfg, self._mesh,
                packed_eos_id=self._packed_eos_id))
            nll_total += float(nll)
            n_total += int(n)
        loss = nll_total / max(n_total, 1)
        return {"eval_loss": loss, "ppl": math.exp(min(loss, 50.0))}

    def close(self):
        self._loader.close()


def evaluate(params, cfg: ModelConfig, mesh, data_path, *, batch: int,
             seq_len: int, max_batches: int = 32, seed: int = 1):
    """One-shot convenience wrapper around Evaluator."""
    del seed  # sequential eval is deterministic; kept for API stability
    ev = Evaluator(cfg, mesh, data_path, batch=batch, seq_len=seq_len,
                   max_batches=max_batches)
    try:
        return ev(params)
    finally:
        ev.close()
