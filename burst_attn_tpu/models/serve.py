"""Continuous-batching serving engine over the paged KV stack.

`models/paged_decode.py` provides the primitives (page pool, ragged paged
attention, prefill/decode steps); this module is the host-side ENGINE a
server actually runs:

  * `ServeEngine.submit(tokens, max_new_tokens)` queues a request.
  * `ServeEngine.step()` advances the world by one token: admits queued
    requests into free slots whenever the pool can cover their prompt AND
    their whole decode budget (admission control = page accounting, so a
    mid-generation OOM is impossible by construction), runs ONE jitted
    decode step for every live slot, retires finished sequences (EOS or
    budget), and returns the newly finished (id, tokens) pairs.
  * `ServeEngine.run()` loops `step()` until no work remains.

Design notes (TPU-shaped):
  * Device arrays never change shape — admission/retirement only rewrites
    the page table and lengths, so the decode step stays one compiled
    program no matter how requests come and go (paged_decode.py's core
    contract).
  * All per-slot bookkeeping (budgets, emitted tokens, EOS checks) is
    host-side python over ONE [slots] logits fetch per step — the engine
    adds no device chatter beyond the step itself.
  * Sampling uses decode.sample_logits on-device for the whole batch;
    per-slot temperature is intentionally NOT supported (it would split
    the batch into per-slot programs).
  * Speculative serving (`draft_params`/`draft_cfg`/`spec_k`): a draft
    model with its own mirrored paged state proposes spec_k tokens per
    slot per tick; ONE paged_multi_step scores every slot's k+1
    positions, per-slot acceptance keeps the matching prefix + one
    target token, and both states roll back with a pure lengths
    decrement.  Greedy only; per-request output is token-exact with the
    non-speculative engine (tested).

Reference parity: none — the reference is an attention op library with no
serving story (SURVEY.md §5); this is framework surface beyond it.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..obs import trace as tracing
from ..admission import (
    AdmissionPolicy, InvalidRequest, LoadShed, RejectReason, SubmitRejected,
    SubmitResult,
)

logger = obs.get_logger(__name__)

# -- engine metrics (module-level: the registry aggregates per process, so
# several engines in one server share one catalog; all host-side code).
# TTFT = submit -> first token available (prefill samples it at admission);
# token latency = engine-tick seconds per token added to a live stream.
_M_SUBMITTED = obs.counter("serve.requests_submitted")
_M_REJECTED = obs.counter("serve.requests_rejected",
                          "submissions refused up front, by reason")
_M_ADMITTED = obs.counter("serve.requests_admitted")
_M_RETIRED = obs.counter("serve.requests_retired",
                         "finished requests, by cause (eos | budget)")
_M_STEPS = obs.counter("serve.engine_steps")
_M_TOKENS = obs.counter("serve.tokens_generated")
_M_QUEUE = obs.gauge("serve.queue_depth")
_M_LIVE = obs.gauge("serve.live_slots")
_M_POOL = obs.gauge("serve.page_pool_occupancy",
                    "fraction of usable pool pages currently held")
_M_SPEC_RATE = obs.gauge("serve.spec_acceptance_rate")
_M_TTFT = obs.histogram("serve.ttft_s")
_M_TOK_LAT = obs.histogram("serve.token_latency_s")
# host time the tick spent OUTSIDE the device launch+sample window, as a
# fraction of launch-tick wall time (cumulative).  Upper bound on the gap
# async pipelining (ROADMAP item 3) could hide: admission bookkeeping and
# retirement count as host, the main launch through its sample sync counts
# as device.  Always on — host clock reads never touch the jaxpr.
_M_HOST_GAP = obs.gauge("serve.host_gap_fraction",
                        "host gap seconds / launch-tick wall seconds")

from .decode import sample_logits
from .paged_decode import (
    PrefixCache, init_paged_state, paged_decode_step, paged_multi_step,
    paged_prefill, provision_capacity, retire_slot,
)
from .transformer import ModelConfig


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray          # [T] int32
    max_new_tokens: int
    tokens: List[int] = field(default_factory=list)  # generated so far
    t_submit: float = 0.0       # perf_counter at submit (TTFT anchor)


class ServeEngine:
    """Host-side continuous-batching loop.  Not thread-safe; drive it from
    one thread (the usual asyncio/executor server pattern)."""

    def __init__(self, params, cfg: ModelConfig, *, slots: int, n_pages: int,
                 page: int = 128, max_pages_per_seq: int = 64,
                 quantize: bool = False, mesh=None, eos_id: Optional[int] = None,
                 temperature: float = 0.0, top_k=None, top_p=None, rng=None,
                 prefix_cache: bool = False, draft_params=None,
                 draft_cfg: Optional[ModelConfig] = None, spec_k: int = 4,
                 max_queue: Optional[int] = None,
                 admission: Optional[AdmissionPolicy] = None,
                 journal=None):
        self.params = params
        self.cfg = cfg
        self.mesh = mesh
        self.eos_id = eos_id
        self.page = page
        self.max_queue = max_queue
        self.admission = admission
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        # optional write-ahead TokenJournal (serving/checkpoint.py): token
        # appends / done / reset records per tick, fsynced once per step()
        # BEFORE results are returned — crash recovery resumes from here
        self.journal = journal
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.state, self.pool = init_paged_state(
            cfg, slots=slots, n_pages=n_pages, page=page,
            max_pages_per_seq=max_pages_per_seq, quantize=quantize)
        self.cache = PrefixCache(self.pool) if prefix_cache else None
        # speculative serving: a DRAFT model with its own paged state whose
        # slot geometry mirrors the target's; greedy only (acceptance =
        # target argmax match — see step()); int8 pools compose (rolled-
        # back tokens' stale scales are as invisible as their K/V)
        self.draft = None
        self.spec_k = 0
        if draft_params is not None:
            if draft_cfg is None:
                raise ValueError("draft_params needs draft_cfg")
            if mesh is not None or temperature != 0.0:
                raise ValueError("speculative serving requires no tp mesh "
                                 "and temperature == 0")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft and target must share a vocabulary")
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self.draft = (draft_params, draft_cfg)
            self.spec_k = spec_k
            self.dstate, self.dpool = init_paged_state(
                draft_cfg, slots=slots, n_pages=n_pages, page=page,
                max_pages_per_seq=max_pages_per_seq, quantize=quantize)
        self.slots: List[Optional[_Request]] = [None] * slots
        self._next_tok = np.zeros((slots,), np.int32)
        self._queue: List[_Request] = []
        self._next_id = 0
        self._finished: Dict[int, List[int]] = {}
        # speculative accounting (draft mode only): proposed counts every
        # draft token scored by the target; accepted counts those MATCHED
        # by the target's argmax (before budget/EOS trims — trims are a
        # serving artifact, not a draft-quality signal).  accepted/proposed
        # is THE quantity a deployed draft is tuned on (Leviathan's alpha)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rounds = 0

    # -- client surface ----------------------------------------------------

    def _reject(self, exc_cls, reason: RejectReason, message: str):
        _M_REJECTED.inc(reason=reason.value)
        raise exc_cls(reason, message)

    def _occupancy(self) -> float:
        """Live pool occupancy, the same value `serve.page_pool_occupancy`
        exports (fraction of usable pages held; page 0 is the sink)."""
        usable = self.pool.n_pages - 1
        return (usable - self.pool.available) / usable if usable else 0.0

    def submit(self, tokens, max_new_tokens: int) -> int:
        """Queue a prompt; returns a request id (tokens appear in
        step() results / results() once finished).

        Raises InvalidRequest (a ValueError) on malformed / permanently
        unservable requests; with `max_queue` or an `admission` policy
        set, raises LoadShed (a RuntimeError) when shed — pool pressure
        (`pool-exhausted`) sheds BEFORE queue pressure (`queue-full`),
        hard exhaustion before the policy's hysteresis sheds
        (`admission-pool` / `admission-queue`).  Every rejection carries
        a typed `.reason` matching its `serve.requests_rejected{reason}`
        label; `try_submit()` is the non-raising surface."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size == 0:
            self._reject(InvalidRequest, RejectReason.EMPTY_PROMPT,
                         "empty prompt")
        if max_new_tokens < 1:
            self._reject(InvalidRequest, RejectReason.BAD_BUDGET,
                         f"max_new_tokens must be >= 1, got "
                         f"{max_new_tokens} (prefill always samples one)")
        need = self._pages_for(tokens.size, max_new_tokens)
        if need > self.state.page_table.shape[1]:
            self._reject(InvalidRequest, RejectReason.TABLE_WIDTH,
                         f"request needs {need} pages > max_pages_per_seq "
                         f"{self.state.page_table.shape[1]}")
        if need > self.pool.n_pages - 1:  # page 0 is the reserved sink
            # a permanently unservable request would deadlock the FIFO
            # queue (admission waits forever for pages that cannot exist)
            self._reject(InvalidRequest, RejectReason.POOL_SIZE,
                         f"request needs {need} pages but the pool only has "
                         f"{self.pool.n_pages - 1} usable pages total")
        if self.max_queue is not None:
            # load shed, POOL pressure before QUEUE pressure: a request
            # that would wait behind others for pages that are not free
            # only deepens the backlog, whatever the queue depth; a full
            # queue is only the reason when pages were never short
            if self._queue and need > self.pool.available:
                self._reject(LoadShed, RejectReason.POOL_EXHAUSTED,
                             f"load shed (pool-exhausted): request needs "
                             f"{need} pages, {self.pool.available} free, "
                             f"{len(self._queue)} already waiting")
            if len(self._queue) >= self.max_queue:
                self._reject(LoadShed, RejectReason.QUEUE_FULL,
                             f"load shed (queue-full): {len(self._queue)} "
                             f"waiting >= max_queue {self.max_queue}")
        if self.admission is not None:
            occ = self._occupancy()
            reason = self.admission.decide(queue_depth=len(self._queue),
                                           pool_occupancy=occ)
            if reason is not None:
                self._reject(LoadShed, reason,
                             f"load shed ({reason}): admission policy — "
                             f"queue_depth={len(self._queue)}, "
                             f"pool_occupancy={occ:.3f}")
        rid = self._next_id
        self._next_id += 1
        req = _Request(rid, tokens, max_new_tokens,
                       t_submit=time.perf_counter())
        # the trace context rides as an attribute, not a dataclass field —
        # checkpoint serialization must not see it (same as _prefix_hashes)
        req._tc = tracing.start_request(rid)
        self._queue.append(req)
        _M_SUBMITTED.inc()
        _M_QUEUE.set(len(self._queue))
        return rid

    def try_submit(self, tokens, max_new_tokens: int) -> SubmitResult:
        """Non-raising submit for routers: rid on success, typed reason
        (with its `retryable` bit) on rejection."""
        try:
            return SubmitResult(rid=self.submit(tokens, max_new_tokens))
        except SubmitRejected as e:
            return SubmitResult(reason=e.reason, message=str(e))

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def live(self) -> int:
        return sum(r is not None for r in self.slots)

    def results(self) -> Dict[int, List[int]]:
        return dict(self._finished)

    @property
    def acceptance_rate(self) -> Optional[float]:
        """Speculative acceptance rate: fraction of proposed draft tokens
        the target's argmax MATCHED (pre-trim — see the counter comment in
        __init__), over the engine's lifetime; None before any speculative
        round.  ~0 means the draft is useless (every round pays k draft
        steps + one multi-token target pass for one kept token); a
        deployed draft is tuned until k*rate > the draft's relative
        cost."""
        if self.spec_proposed == 0:
            return None
        return self.spec_accepted / self.spec_proposed

    def run(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Drive step() until every submitted request finishes."""
        with obs.span("serve.run"):
            for _ in range(max_steps):
                if not self._queue and self.live == 0:
                    return self.results()
                self.step()
        raise RuntimeError(f"run() exceeded {max_steps} steps")

    def drain(self) -> List[int]:
        """Graceful shutdown: release every in-flight slot's pages and put
        its request BACK at the queue head (generated tokens reset; the
        prefill re-samples the identical first token under greedy
        decoding), then refresh the gauges so a drained engine reads
        live=0 / occupancy=0.  Returns the requeued rids in their new
        queue order.  The engine stays usable — run() after drain()
        serves everything, requeued work first, to completion."""
        inflight = [req for req in self.slots if req is not None]
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            self.state = retire_slot(self.state, self.pool, slot)
            if self.draft is not None:
                self.dstate = retire_slot(self.dstate, self.dpool, slot)
            self.slots[slot] = None
        inflight.sort(key=lambda r: r.rid)
        for req in reversed(inflight):
            req.tokens = []
            self._queue.insert(0, req)
            if self.journal is not None:
                self.journal.reset(req.rid)
        if self.journal is not None:
            self.journal.sync()
        _M_QUEUE.set(len(self._queue))
        _M_LIVE.set(0)
        _M_POOL.set(self._occupancy())
        return [r.rid for r in inflight]

    # -- engine ------------------------------------------------------------

    def _pages_for(self, prompt_len: int, max_new: int) -> int:
        # speculative verification transiently appends spec_k + 1 tokens
        # past the budget before rolling back — capacity must cover it
        slack = self.spec_k + 1 if self.draft is not None else 0
        return -(-(prompt_len + max_new + slack) // self.page)

    def _admit(self) -> None:
        """Move queued requests into free slots while the pool can cover
        their FULL lifetime (prompt pages now + decode pages provisioned
        up front — admission is the only allocation point)."""
        for slot, occupant in enumerate(self.slots):
            if occupant is not None or not self._queue:
                continue
            req = self._queue[0]
            need = self._pages_for(len(req.prompt), req.max_new_tokens)
            if need > self.pool.available and self.cache is not None:
                # cached pages not referenced by live sequences free up here
                # (LRU); the need estimate is cache-blind, so this can evict
                # prefixes the request would have reused — correct, just
                # conservative under pressure
                self.cache.evict(need - self.pool.available)
            if need > self.pool.available:
                break  # FIFO: don't starve the head by admitting behind it
            if self.draft is not None and need > self.dpool.available:
                # the draft pool duplicates pages the target may be sharing
                # via the prefix cache; admitting on the target check alone
                # could fail the draft prefill MID-admission and wedge the
                # slot (target live, request lost)
                break
            slack = self.spec_k + 1 if self.draft is not None else 0
            t_adm = time.perf_counter()  # queued ends / prefill starts here
            try:
                logits, self.state = paged_prefill(
                    self.params, jnp.asarray(req.prompt), self.state,
                    self.pool, slot, self.cfg, mesh=self.mesh,
                    cache=self.cache)
                self.state = provision_capacity(
                    self.state, self.pool, slot, req.max_new_tokens + slack)
                if self.draft is not None:
                    dp, dc = self.draft
                    _, self.dstate = paged_prefill(
                        dp, jnp.asarray(req.prompt), self.dstate, self.dpool,
                        slot, dc)
                    self.dstate = provision_capacity(
                        self.dstate, self.dpool, slot,
                        req.max_new_tokens + slack)
            except Exception:
                # paged_prefill / provision_capacity release their own
                # MID-CALL acquisitions, but pages committed to the table by
                # an earlier successful call in this block (e.g. the target
                # prefill before a draft-side raise) belong to a slot that
                # slots[slot] will never point at — unreachable by
                # _retire_finished, leaked on every retry.  retire_slot is a
                # no-op on a state the failure left empty, so retire both —
                # BEST-EFFORT: a runtime failure INSIDE a donating prefill
                # jit deletes the very buffers retire_slot would read
                # (donate_argnums; paged_decode.py's donation contract), and
                # that secondary raise must not mask the original error.
                # Host-side failures (pool exhaustion, table width — the
                # only ones the engine can survive) roll back cleanly.
                try:
                    self.state = retire_slot(self.state, self.pool, slot)
                except Exception as rollback_err:  # noqa: BLE001
                    # non-fatal (deleted donated buffers), but an UNEXPECTED
                    # rollback failure here is a silent page leak — log it
                    logger.warning(
                        "admission rollback: retire_slot(slot=%d) failed "
                        "(%s: %s); continuing — pages may leak if this is "
                        "not the deleted-donated-buffer case",
                        slot, type(rollback_err).__name__, rollback_err)
                if self.draft is not None:
                    try:
                        self.dstate = retire_slot(self.dstate, self.dpool,
                                                  slot)
                    except Exception as rollback_err:  # noqa: BLE001
                        logger.warning(
                            "admission rollback: draft retire_slot(slot=%d) "
                            "failed (%s: %s); continuing",
                            slot, type(rollback_err).__name__, rollback_err)
                raise
            tok = self._sample(logits[None, :])[0]
            if tok < 0:  # sample_logits NaN-poison sentinel
                # roll the half-admitted slot back BEFORE raising: the
                # prefill + provision above already allocated pages for a
                # slot that slots[slot] will never point at — without the
                # retire they would be unreachable by _retire_finished and
                # leak on every failed admission attempt
                self.state = retire_slot(self.state, self.pool, slot)
                if self.draft is not None:
                    self.dstate = retire_slot(self.dstate, self.dpool, slot)
                raise RuntimeError(
                    f"slot {slot} (rid {req.rid}) prefill logits are "
                    "NaN-poisoned")
            # dequeue only once every prefill + provision + the sample's
            # poison check succeeded: a runtime failure above leaves the
            # request at the queue head (with its pages rolled back)
            # instead of silently dropping it
            self._queue.pop(0)
            req.tokens.append(int(tok))
            if self.journal is not None:
                self.journal.tokens(req.rid, [int(tok)])
            self.slots[slot] = req
            self._next_tok[slot] = int(tok)
            now = time.perf_counter()
            # the prefill+sample block was device-bound: credit it to the
            # tick's device window so host_gap_fraction stays honest on
            # admission-heavy ticks
            self._tick_dev_s = getattr(self, "_tick_dev_s", 0.0) \
                + (now - t_adm)
            _M_ADMITTED.inc()
            _M_TOKENS.inc()  # the prefill-sampled first token
            _M_TTFT.observe(now - req.t_submit)
            _M_QUEUE.set(len(self._queue))
            tc = getattr(req, "_tc", None)
            if tc is not None:
                # lifecycle phases are CONTIGUOUS on one clock (queued ends
                # where prefill starts, prefill ends at the first-token
                # instant), so the critical-path breakdown sums to the
                # observed TTFT by construction
                req._t_first = now
                tracing.record_span(tc, "serve.queued", req.t_submit, t_adm)
                tracing.record_span(tc, "serve.prefill", t_adm, now)
                tracing.marker(tc, "serve.first_token", now)
                tracing.note_ttft(tc, now - req.t_submit)
                tracing.publish_breakdown({"queued": t_adm - req.t_submit,
                                           "prefill": now - t_adm})

    def _sample(self, logits):
        self._rng, key = jax.random.split(self._rng)
        # nan_sentinel: poisoned rows (paged loud-failure contract) come
        # back as -1 so the engine can raise without a second logits fetch
        return np.asarray(sample_logits(
            logits, key, temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, nan_sentinel=True))

    def _retire_finished(self) -> List[Tuple[int, List[int]]]:
        done = []
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            hit_eos = self.eos_id is not None and req.tokens \
                and req.tokens[-1] == self.eos_id
            if hit_eos or len(req.tokens) >= req.max_new_tokens:
                self.state = retire_slot(self.state, self.pool, slot)
                if self.draft is not None:
                    self.dstate = retire_slot(self.dstate, self.dpool, slot)
                self.slots[slot] = None
                self._finished[req.rid] = req.tokens
                done.append((req.rid, req.tokens))
                if self.journal is not None:
                    self.journal.done(req.rid)
                _M_RETIRED.inc(cause="eos" if hit_eos else "budget")
                tc = getattr(req, "_tc", None)
                if tc is not None:
                    now = time.perf_counter()
                    tracing.record_span(
                        tc, "serve.decode",
                        getattr(req, "_t_first", req.t_submit), now,
                        tokens=len(req.tokens))
                    tracing.record_span(tc, "serve.request", req.t_submit,
                                        now, root=True, rid=req.rid)
        return done

    def _note_tick(self, dt: float, added: int,
                   dev_s: Optional[float] = None) -> None:
        """Per-tick obs update: queue/slot/pool gauges and, when tokens were
        produced, the amortized per-token latency (tick seconds per token
        per stream: live streams advance concurrently, so each stream's
        tokens arrived `dt / (added / live)` apart).  `dev_s` is the tick's
        device launch+sample window; when known, the remainder feeds the
        cumulative `serve.host_gap_fraction` gauge."""
        if dev_s is not None:
            self._host_gap_s = getattr(self, "_host_gap_s", 0.0) \
                + max(0.0, dt - dev_s)
            self._launch_wall_s = getattr(self, "_launch_wall_s", 0.0) + dt
            _M_HOST_GAP.set(self._host_gap_s / self._launch_wall_s)
        _M_STEPS.inc()
        _M_QUEUE.set(len(self._queue))
        live = self.live
        _M_LIVE.set(live)
        usable = self.pool.n_pages - 1  # page 0 is the reserved sink
        _M_POOL.set((usable - self.pool.available) / usable if usable else 0.0)
        if added:
            _M_TOKENS.inc(added)
            _M_TOK_LAT.observe(dt * live / added)
        rate = self.acceptance_rate
        if rate is not None:
            _M_SPEC_RATE.set(rate)

    def step(self) -> List[Tuple[int, List[int]]]:
        """One engine tick (see _step).  When a journal is attached this
        is also the durability barrier: the tick's journal appends are
        fsynced BEFORE its results are returned, so any token a caller
        has seen survives a crash (write-ahead)."""
        done = self._step()
        if self.journal is not None:
            self.journal.sync()
            # delivery barrier (protocols.journal): every stream leaving
            # this tick must already be durable, or delivered() raises
            for rid, toks in done:
                self.journal.delivered(rid, len(toks))
        return done

    def _step(self) -> List[Tuple[int, List[int]]]:
        """One engine tick: retire -> admit -> one decode advance for every
        live slot (a single token, or a whole speculative round when a
        draft model is attached).  Returns requests that finished THIS
        tick.

        Admit and retire alternate until stable: a freshly admitted request
        can already be complete (max_new_tokens == 1, or the prefill-sampled
        token IS eos) and must retire — and free its slot for the next
        queued request — WITHOUT running a decode step, or it would receive
        a token past its budget / past EOS and break parity with
        generate()."""
        t0 = time.perf_counter()
        self._tick_dev_s = 0.0  # _admit credits its prefill windows here
        done = self._retire_finished()
        while True:
            before = self.pending
            self._admit()
            done += self._retire_finished()
            if self.pending == before:
                break
        if self.live == 0:
            self._note_tick(time.perf_counter() - t0, 0,
                            self._tick_dev_s or None)
            return done
        if self.draft is not None:
            td0 = time.perf_counter()
            added = self._spec_round()
            # the whole round counts as device window (its launches are
            # back-to-back; the python glue between them is noise here)
            self._tick_dev_s += time.perf_counter() - td0
            self._note_tick(time.perf_counter() - t0, added,
                            self._tick_dev_s)
            return done
        td0 = time.perf_counter()
        logits, self.state = paged_decode_step(
            self.params, jnp.asarray(self._next_tok), self.state, self.cfg,
            mesh=self.mesh)
        toks = self._sample(logits)  # host sync: the device window ends here
        self._tick_dev_s += time.perf_counter() - td0
        added = 0
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if toks[slot] < 0:  # sample_logits NaN-poison sentinel
                raise RuntimeError(
                    f"slot {slot} (rid {req.rid}) logits are NaN-poisoned: "
                    "a live slot was stepped without provisioned capacity")
            req.tokens.append(int(toks[slot]))
            if self.journal is not None:
                self.journal.tokens(req.rid, [int(toks[slot])])
            self._next_tok[slot] = int(toks[slot])
            added += 1
        self._note_tick(time.perf_counter() - t0, added, self._tick_dev_s)
        return done

    def _spec_round(self) -> int:
        """One speculative round for EVERY live slot: the draft proposes
        spec_k tokens per slot (k single paged steps on its own state);
        the target scores all k+1 positions in ONE paged_multi_step; each
        slot keeps its matching prefix + one target token, then both
        states roll back to exactly the kept tokens (a lengths decrement —
        entries past lengths are invisible).  Greedy: per-slot output is
        token-exact with the non-speculative engine.  Returns the total
        number of tokens kept across slots (obs per-token latency)."""
        k = self.spec_k
        dp, dc = self.draft
        # draft proposals stay ON DEVICE across the k steps (one transfer
        # after the loop — per-step np.asarray would serialize each step on
        # a host roundtrip)
        toks_dev = []
        cur = jnp.asarray(self._next_tok)
        # draft-side poison accumulator: stays on device across the k steps
        # (a per-step host check would serialize the loop on round trips)
        bad_d = jnp.zeros(len(self.slots), bool)
        for i in range(k):
            lg_d, self.dstate = paged_decode_step(dp, cur, self.dstate, dc)
            bad_d = bad_d | jnp.any(jnp.isnan(lg_d), axis=-1)
            cur = jnp.argmax(lg_d, axis=-1).astype(jnp.int32)
            toks_dev.append(cur)
        d_toks_dev = jnp.stack(toks_dev, axis=1)            # [slots, k]
        # target verifies [last | proposals] in one multi-token pass
        feed = jnp.concatenate(
            [jnp.asarray(self._next_tok)[:, None], d_toks_dev], axis=1)
        lg_t, self.state = paged_multi_step(
            self.params, feed, self.state, self.cfg)
        # draft catch-up: after proposing it holds [last | d0..dk-2]; one
        # uniform step feeding dk-1 brings every slot to base + k + 1,
        # matching the target — the vectorized rollback then trims both
        _, self.dstate = paged_decode_step(
            dp, d_toks_dev[:, -1], self.dstate, dc)
        self.spec_rounds += 1
        # the round's bulk host sync: proposals + target choices together
        d_toks = np.asarray(d_toks_dev)
        choice = np.asarray(jnp.argmax(lg_t, axis=-1))      # [slots, k+1]
        # loud-failure contract: paged_multi_step / the draft's decode steps
        # NaN-poison a live slot stepped past its provisioned pages; argmax
        # would silently read 0 (draft-side: 0-acceptance forever)
        bad = np.asarray(jnp.any(jnp.isnan(lg_t), axis=(1, 2)) | bad_d)
        undo = np.zeros(len(self.slots), np.int32)
        n_kept = 0
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if bad[slot]:
                raise RuntimeError(
                    f"slot {slot} (rid {req.rid}) speculative logits are "
                    "NaN-poisoned: stepped without provisioned capacity")
            n_acc = 0
            while n_acc < k and d_toks[slot, n_acc] == choice[slot, n_acc]:
                n_acc += 1
            self.spec_proposed += k
            self.spec_accepted += n_acc
            new = ([int(x) for x in d_toks[slot, :n_acc]]
                   + [int(choice[slot, n_acc])])
            # budget and EOS trims (a speculative round can overshoot both)
            new = new[: req.max_new_tokens - len(req.tokens)]
            if self.eos_id is not None and self.eos_id in new:
                new = new[: new.index(self.eos_id) + 1]
            req.tokens += new
            if self.journal is not None:
                self.journal.tokens(req.rid, new)
            n_kept += len(new)
            self._next_tok[slot] = new[-1]
            undo[slot] = k + 1 - len(new)  # both states appended k+1
        # ONE vectorized lengths-subtract per state (dead slots undo 0).
        # Intentionally NOT rollback_tokens: its per-slot n < length guard
        # is satisfied by construction here (live slots keep >= 1 token)
        # and per-slot calls would cost a host fetch + dispatch each
        undo_dev = jnp.asarray(undo)
        self.state = self.state._replace(lengths=self.state.lengths - undo_dev)
        self.dstate = self.dstate._replace(
            lengths=self.dstate.lengths - undo_dev)
        return n_kept
