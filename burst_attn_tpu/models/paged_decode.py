"""Paged KV-cache serving path: shared page pool + ragged continuous
batching on top of ops/paged_attention.py.

models/decode.py allocates a dense [B, Nkv, max_seq, D] cache per layer —
worst-case memory per sequence, O(max_seq) decode compute, and batch slots
are all-or-nothing.  This module is the serving-shaped alternative:

  * `PagePool` (host-side, stateful): owns the free list of pool pages.
    Sequences acquire pages as they grow and release them on retirement —
    admission control falls out of `len(free)`.
  * `PagedState` (device pytree): per-layer page pools, the page table,
    per-sequence lengths, everything static-shaped — the host mutates the
    TABLE (tiny int32 arrays), never reshapes device buffers, so the jitted
    step functions never retrace as sequences come and go.
  * `paged_prefill` absorbs a prompt into freshly-acquired pages (flash
    attention over the contiguous prompt, then paged scatter of the rope'd
    K/V); `paged_decode_step` appends one token per live sequence and
    attends via the ragged paged kernel.  Sequences at different lengths
    batch in the same call (ragged), empty slots cost one predicated grid
    step per page slot.

The batch dimension is a fixed number of SLOTS (max concurrent sequences);
continuous batching = host assigns a finished slot's pages back to the free
list and prefillls a new prompt into that slot, while other slots keep
decoding.  Slot admission/retirement is host logic between steps — the
device arrays never change shape.

Reference parity: the reference has no serving layer at all (SURVEY.md §5
"checkpoint/resume: none (op library)"); this extends the framework the
same direction as models/decode.py but with pool semantics.  Kernel design
notes in ops/paged_attention.py.
"""

from collections import OrderedDict
from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.sharding import PartitionSpec as P

from .transformer import ModelConfig, _attn_out, _mlp, _qkv_proj, _rms_norm
from .decode import _flash_prompt_attention, sample_logits
from ..ops.paged_attention import (
    QUANT_DTYPES, paged_decode_attention, quantize_tokens,
)
from ..utils.compat import shard_map


def resolve_pool_dtype(quantize, default):
    """(pool storage dtype, canonical tag) for an init_paged_state-style
    `quantize` knob: False -> (default, None); True / "int8" -> int8;
    "fp8" -> float8_e4m3fn.  The tag is the string every downstream
    surface keys on (obs labels, checkpoint meta, kvplane wire meta)."""
    if not quantize:
        return default, None
    name = "int8" if quantize is True else str(quantize)
    if name not in QUANT_DTYPES:
        raise ValueError(f"quantize must be False, True, or one of "
                         f"{sorted(QUANT_DTYPES)}; got {quantize!r}")
    return QUANT_DTYPES[name][0], name


def _check_tp_mesh(cfg: ModelConfig, mesh):
    """Shared head-axis validation for the tp serving paths; returns the
    tp size (1 = run unsharded)."""
    if mesh is None or cfg.head_axis is None:
        return 1
    if cfg.head_axis not in mesh.shape:
        raise ValueError(
            f"head_axis {cfg.head_axis!r} is not an axis of the mesh "
            f"{dict(mesh.shape)}; pass mesh=None for single-device serving "
            "or set cfg.head_axis to a mesh axis")
    tp = mesh.shape.get(cfg.head_axis, 1)
    if tp > 1 and (cfg.n_kv_heads % tp or cfg.n_heads % tp):
        raise ValueError(
            f"n_heads {cfg.n_heads} / n_kv_heads {cfg.n_kv_heads} not "
            f"divisible by {cfg.head_axis!r} mesh size {tp}")
    return tp


def _prompt_attention_dispatch(q, k, v, cfg: ModelConfig, mesh):
    """Head-sharded prompt (prefill) attention under a tp mesh — same
    rationale as _paged_attention_dispatch: the Pallas flash call must be
    split explicitly."""
    if _check_tp_mesh(cfg, mesh) == 1:
        return _flash_prompt_attention(q, k, v, window=cfg.window)
    spec = P(None, cfg.head_axis, None, None)
    fn = shard_map(
        partial(_flash_prompt_attention, window=cfg.window),
        mesh=mesh,
        in_specs=(spec,) * 3,
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def _paged_attention_dispatch(qg, kp, vp, ks, vs, table, lengths,
                              cfg: ModelConfig, mesh):
    """Route the paged kernel through a head-sharded shard_map when serving
    tensor-parallel (mesh given and cfg.head_axis present): the pool's kv
    heads split over tp, each shard walks its own pages — a Pallas call
    cannot be partitioned by GSPMD, so the split must be explicit.  The
    table/lengths ride in replicated.  Everything else in the step (qkv
    projections, MLP, logits) stays GSPMD-sharded by the params' specs."""
    if _check_tp_mesh(cfg, mesh) == 1:
        return paged_decode_attention(qg, kp, vp, table, lengths,
                                      k_scales=ks, v_scales=vs,
                                      window=cfg.window)
    spec4 = P(None, cfg.head_axis, None, None)
    spec3 = P(None, cfg.head_axis, None)
    quant = ks is not None
    in_specs = [spec4, spec4, spec4]
    args = [qg, kp, vp]
    if quant:
        in_specs += [spec3, spec3]
        args += [ks, vs]
    in_specs += [P(None, None), P(None)]
    args += [table, lengths]

    def shard(qg, kp, vp, *rest):
        if quant:
            ks_l, vs_l, table_l, lengths_l = rest
        else:
            ks_l, vs_l = None, None
            table_l, lengths_l = rest
        return paged_decode_attention(qg, kp, vp, table_l, lengths_l,
                                      k_scales=ks_l, v_scales=vs_l,
                                      window=cfg.window)

    fn = shard_map(
        shard, mesh=mesh, in_specs=tuple(in_specs), out_specs=spec4,
        check_vma=False,
    )
    return fn(*args)


class PagedState(NamedTuple):
    """Device-side paged cache (one pool per layer, table shared).
    Quantized serving (init_paged_state(quantize=True | "int8" | "fp8")):
    pools store 1 B/elem (int8 or fp8 e4m3fn) with per-token fp32 dequant
    scales beside the pages — half the bf16 pool memory, a quarter of
    fp32.  The scale banks are pool state exactly like the page bytes:
    CoW copies, checkpoints, and KV-plane shipments carry both or
    neither."""
    k_pages: Tuple[jax.Array, ...]  # each [P, Nkv, page, D]
    v_pages: Tuple[jax.Array, ...]
    page_table: jax.Array           # [slots, max_pages_per_seq] int32
    lengths: jax.Array              # [slots] int32 (0 = empty slot)
    k_scales: Optional[Tuple[jax.Array, ...]] = None  # each [P, Nkv, page]
    v_scales: Optional[Tuple[jax.Array, ...]] = None


class PagePool:
    """Host-side REFCOUNTED page allocator for a PagedState.

    Not a jax object: allocation decisions happen between jitted steps.
    `acquire(n)` pops page ids from the free list at refcount 1 (raises if
    exhausted — callers use `available` for admission control);
    `release(ids)` decrements and returns a page to the free list when its
    count reaches zero; `share(ids)` increments (prefix caching: the same
    physical page referenced from several sequences' table rows and/or the
    prefix cache).  The pool never touches device memory: pages are
    recycled by table rewrite, stale contents are simply never addressed.

    Every mutation runs through the PURE transition function
    `protocols.pool.step` — the same function burstcheck's model checker
    explores over all interleavings (proto-pool-conserved) — with
    `_free`/`_refs` kept as the mutable mirror of the machine state
    (checkpoint serialization and the fuzz integrity recount read them
    directly).
    """

    def __init__(self, n_pages: int, dtype: Optional[str] = None):
        # page 0 is RESERVED as the write sink for empty batch slots: the
        # jitted decode step must scatter *something* per slot (static
        # shapes), and routing dead slots' writes to a page no sequence can
        # own keeps live pages clobber-free without per-slot predication.
        self.n_pages = n_pages
        # the STORAGE dtype tag of the pools this allocator fronts:
        # None = full precision, "int8"/"fp8" = 1 B pages + scale banks.
        # Pure metadata here (the allocator never touches device memory),
        # but it is the single tag obs gauges label by, checkpoints pin,
        # and the KV plane asserts agreement on before landing pages.
        self.dtype = dtype
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._refs = [0] * n_pages

    def proto_state(self):
        """The allocator as the machine's immutable PoolState."""
        from ..protocols import pool as _pp

        return _pp.from_lists(self.n_pages, self._free, self._refs)

    def _step(self, event):
        from ..protocols import pool as _pp

        st, out = _pp.step(self.proto_state(), event)
        self._free = list(st.free)
        self._refs = list(st.refs)
        return out

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        """PHYSICAL pages currently held (each shared page counts once)."""
        return self.n_pages - 1 - len(self._free)

    @property
    def logical_refs(self) -> int:
        """Sum of refcounts — the pages the pool would need WITHOUT
        sharing.  logical_refs - in_use = pages saved by prefix sharing."""
        return sum(self._refs)

    @property
    def has_shared(self) -> bool:
        """True iff ANY page is held at refcount > 1 — the cheap gate the
        serving engine uses to skip the CoW barrier scan entirely when
        nothing is shared (the common cache-off / zero-overlap case)."""
        return any(r > 1 for r in self._refs)

    def refcount(self, i: int) -> int:
        return self._refs[int(i)]

    def acquire(self, n: int) -> List[int]:
        out = self._step(("acquire", int(n)))
        return list(out[0][1])

    def share(self, ids) -> None:
        """Add one reference to already-live pages (prefix reuse)."""
        self._step(("share", tuple(int(i) for i in ids)))

    def release(self, ids) -> None:
        # an over-release would put the page on the free list while another
        # sequence still references it — corrupt both, silently (the
        # machine validates the whole batch before mutating anything)
        self._step(("release", tuple(int(i) for i in ids)))


class PrefixCache:
    """Host-side page-aligned prefix cache (vLLM-style automatic prefix
    caching, restricted to FULL pages).

    Maps the rolling hash of each full-page token prefix to the pool page
    holding that page's K/V (one page id is valid across every layer's
    pool — the table is layer-shared).  The cache owns ONE pool reference
    per registered page, so cached pages survive their sequences retiring;
    `evict(n)` drops the n least-recently-used entries and their refs.

    Write discipline: the LEGACY full-prefill path (paged_prefill) never
    writes a shared page — decode appends target the column at
    lengths//page, beyond every full (cacheable) page.  The ragged engine
    additionally admits FULL-prompt hits by re-absorbing the prompt's last
    token through chunked prefill, whose K/V scatter targets the last
    shared page — that write goes through the copy-on-write barrier
    (serving/model.cow_pages) which privatizes the page first.  Eviction
    only frees a physical page when its refcount reaches 0.
    """

    def __init__(self, pool: PagePool):
        self._pool = pool
        self._pages: "dict[bytes, int]" = {}   # prefix hash -> page id
        # least recent first; OrderedDict keys give O(1) touch/remove
        # (a plain list made every lookup hit O(n) and evictions O(n^2))
        self._lru: "OrderedDict[bytes, None]" = OrderedDict()
        # chain structure: a lookup stops at the first miss, so an entry
        # whose PARENT is gone can never hit again — eviction must go
        # leaf-first or it orphans reachable descendants
        self._parent: "dict[bytes, Optional[bytes]]" = {}
        self._nkids: "dict[bytes, int]" = {}

    @staticmethod
    def chain(tokens, page: int, dtype: Optional[str] = None) -> List[bytes]:
        """Rolling hash per FULL page of `tokens` (1-D int array): entry i
        identifies the whole prefix tokens[:(i+1)*page].

        `dtype` is the pool's STORAGE dtype tag (PagePool.dtype) and is
        folded into the seed of the chain, making each entry a stable
        content key for the QUANTIZED page bytes: within one pool dtype
        the quantized representation is a deterministic function of the
        token prefix (quantize_tokens is pure), so two prompts share an
        entry iff their pages hold identical quantized bytes — and an
        entry minted against an int8 pool can never alias one minted
        against fp8 or full precision (the requantization hazard across
        checkpoint restores into a differently-typed pool).  dtype=None
        (full precision) keeps the pre-quantization chain byte-identical."""
        import hashlib

        toks = np.asarray(tokens, np.int32)
        out: List[bytes] = []
        h = b"" if dtype is None else f"pool:{dtype}".encode()
        for i in range(len(toks) // page):
            h = hashlib.sha1(h + toks[i * page:(i + 1) * page].tobytes()
                             ).digest()
            out.append(h)
        return out

    def __len__(self):
        return len(self._pages)

    def _touch(self, h: bytes):
        self._lru.move_to_end(h)

    def lookup(self, hashes: List[bytes]) -> List[int]:
        """Longest cached prefix of `hashes`; bumps the pool refcount of
        every returned page (caller owns the new references) and marks the
        entries recently used."""
        ids: List[int] = []
        for h in hashes:
            pid = self._pages.get(h)
            if pid is None:
                break
            ids.append(pid)
            self._touch(h)
        self._pool.share(ids)
        return ids

    def insert(self, hashes: List[bytes], page_ids) -> None:
        """Register a prompt's FULL hash chain (hashes[i]'s parent is
        hashes[i-1]); the cache takes one reference per NEWLY inserted
        page.  Already-present entries are touched (LRU refresh) only."""
        assert len(hashes) == len(page_ids)
        prev: Optional[bytes] = None
        for h, pid in zip(hashes, page_ids):
            if h in self._pages:
                self._touch(h)
            else:
                self._pool.share([int(pid)])
                self._pages[h] = int(pid)
                self._lru[h] = None
                self._parent[h] = prev
                self._nkids[h] = 0
                if prev is not None:
                    self._nkids[prev] += 1
            prev = h

    def evictable(self) -> int:
        """Upper bound on pages evict() could free right now: entries whose
        page only the cache references.  A refcount-1 parent blocked by a
        pinned child is counted but not currently droppable, so callers
        treat this as a shed heuristic, never a guarantee — hard admission
        calls evict() for real and rechecks."""
        return sum(1 for pid in self._pages.values()
                   if self._pool.refcount(pid) == 1)

    def to_meta(self) -> List[List[str]]:
        """JSON-able snapshot of the index: [hash_hex, page_id, parent_hex]
        per entry in LRU order (least recent first).  Pool refcounts are
        NOT included — the pool serializes its own `_refs` wholesale
        (serving/checkpoint._pool_meta), and this index's references are
        part of that total."""
        return [[h.hex(), str(self._pages[h]),
                 (self._parent[h] or b"").hex()]
                for h in self._lru]

    @classmethod
    def from_meta(cls, pool: PagePool, meta) -> "PrefixCache":
        """Rebuild an index captured by to_meta against an already-restored
        pool.  Does NOT call pool.share — the serialized refcounts already
        include this index's references (double-bumping them here would be
        exactly the leak the checkpoint fuzz hunts)."""
        cache = cls(pool)
        for h_hex, pid, parent_hex in meta:
            h = bytes.fromhex(h_hex)
            parent = bytes.fromhex(parent_hex) or None
            pid = int(pid)
            if pool.refcount(pid) < 1:
                raise ValueError(
                    f"prefix-cache meta references free page {pid}")
            cache._pages[h] = pid
            cache._lru[h] = None
            cache._parent[h] = parent
            cache._nkids.setdefault(h, 0)
            if parent is not None:
                cache._nkids[parent] = cache._nkids.get(parent, 0) + 1
        return cache

    def evict(self, n: int) -> int:
        """Free up to n pages by dropping entries, LRU-first among LEAVES
        (an entry with cached children is never dropped first: lookups
        stop at the first miss, so removing a chain root orphans every
        descendant while freeing one page).  Entries whose page a live
        sequence still shares are skipped — releasing them frees nothing
        and destroys reusable prefixes.  Returns pages actually freed."""
        freed = 0
        progress = True
        while freed < n and progress:
            progress = False
            for h in list(self._lru):
                if freed >= n:
                    break
                if self._nkids.get(h, 0) > 0:
                    continue  # not a leaf
                if self._pool.refcount(self._pages[h]) > 1:
                    continue  # shared with a live sequence
                del self._lru[h]
                self._pool.release([self._pages.pop(h)])
                parent = self._parent.pop(h)
                self._nkids.pop(h, None)
                if parent is not None and parent in self._nkids:
                    self._nkids[parent] -= 1
                freed += 1
                progress = True  # a parent may have become a leaf
        return freed


def _suffix_attention(q, k, v, t_pre, q_hi, kv_hi, window=None,
                      use_flash=None):
    """Causal attention of suffix queries (absolute positions t_pre..) over
    the full [cached prefix + suffix] context: one offset MaskSpec — col j
    visible from suffix row i iff j <= i + t_pre — instead of a separate
    kernel (the same five-scalar tile contract the ring rounds use).

    q/k may carry PADDED tail rows/cols (page-multiple shapes keep the
    enclosing jit's compile key at page granularity); the TRACED q_hi /
    kv_hi bounds keep them invisible — pad-row outputs are garbage the
    caller never reads."""
    from ..ops.masks import MaskSpec

    b, n, t_suf, d = q.shape
    s_kv = k.shape[2]
    spec = MaskSpec(jnp.int32(0), jnp.int32(q_hi), jnp.int32(kv_hi),
                    jnp.int32(1), jnp.int32(t_pre))
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash:
        from ..ops.pallas_flash import flash_fwd
        from ..ops.tile import finalize

        # None carry: statically-empty initial state (no zeros round trip)
        m, lse, acc = flash_fwd(q, k, v, None, None, None, d**-0.5, spec,
                                window=window)
        return finalize(m, lse, acc, q.dtype)
    # CPU/tests: dense masked softmax (GQA via repeat; small shapes); the
    # visibility mask comes from the shared oracle (ops/masks.dense_mask)
    # so the band formula stays single-sourced with the kernels
    from ..ops.masks import dense_mask

    group = q.shape[1] // k.shape[1]
    kf = jnp.repeat(k, group, axis=1).astype(jnp.float32)
    vf = jnp.repeat(v, group, axis=1).astype(jnp.float32)
    s = jnp.einsum("bnid,bnjd->bnij", q.astype(jnp.float32), kf) * d**-0.5
    s = jnp.where(dense_mask(spec, t_suf, s_kv, window=window), s,
                  float("-inf"))
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked pad rows softmax to NaN; zero them so downstream
    # layer math (whose pad rows the caller ignores) stays finite
    p = jnp.where(jnp.isnan(p), 0.0, p)
    return jnp.einsum("bnij,bnjd->bnid", p, vf).astype(q.dtype)


def _suffix_attention_dispatch(q, k, v, t_pre, q_hi, kv_hi, cfg, mesh):
    """Head-sharded suffix attention under a tp mesh — same rationale as
    _prompt_attention_dispatch: the Pallas flash call cannot be split by
    GSPMD.  The traced q_hi/kv_hi bounds ride in replicated."""
    if _check_tp_mesh(cfg, mesh) == 1:
        return _suffix_attention(q, k, v, t_pre, q_hi=q_hi, kv_hi=kv_hi,
                                 window=cfg.window)
    spec = P(None, cfg.head_axis, None, None)
    fn = shard_map(
        lambda q_, k_, v_, qh, kh: _suffix_attention(
            q_, k_, v_, t_pre, q_hi=qh, kv_hi=kh, window=cfg.window),
        mesh=mesh,
        in_specs=(spec, spec, spec, P(), P()),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, q_hi, kv_hi)


def init_paged_state(cfg: ModelConfig, *, slots: int, n_pages: int,
                     page: int = 128, max_pages_per_seq: int = 64,
                     quantize=False) -> Tuple[PagedState, PagePool]:
    """Fresh pool + allocator.  `page` must be a multiple of 128 (TPU lane
    tile); total pool capacity is n_pages * page tokens shared by all
    slots.  `quantize`: False = full-precision pools; True or "int8" =
    int8 pools; "fp8" = float8_e4m3fn pools — quantized pools store
    per-token fp32 dequant scales beside the pages."""
    if page % 128:
        raise ValueError(f"page size {page} must be a multiple of 128")
    shape = (n_pages, cfg.n_kv_heads, page, cfg.d_head)
    dt, tag = resolve_pool_dtype(quantize, cfg.dtype)
    k_pages = tuple(jnp.zeros(shape, dt) for _ in range(cfg.n_layers))
    v_pages = tuple(jnp.zeros(shape, dt) for _ in range(cfg.n_layers))
    table = jnp.zeros((slots, max_pages_per_seq), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    ks = vs = None
    if tag is not None:
        ks = tuple(jnp.ones(shape[:3], jnp.float32)
                   for _ in range(cfg.n_layers))
        vs = tuple(jnp.ones(shape[:3], jnp.float32)
                   for _ in range(cfg.n_layers))
    return (PagedState(k_pages, v_pages, table, lengths, ks, vs),
            PagePool(n_pages, dtype=tag))


def _gather_dequant_pages(pages, scales, idx, n_kv, d_head):
    """Gather pool pages page-contiguously, dequantizing when int8:
    idx [..., n] -> [..., n_kv, n*page, d_head].  The ONE place the
    dequant-gather convention lives (suffix prefill + multi-step read
    through it; a dtype/layout change lands in both or neither)."""
    g = pages[idx]
    if scales is not None:
        g = g.astype(jnp.float32) * scales[idx][..., None]
    g = jnp.moveaxis(g, -3, -4)
    return g.reshape(*g.shape[:-4], n_kv, g.shape[-3] * g.shape[-2], d_head)


def _scatter_pages(pages, new, page_ids, scales=None):
    """Write [1, Nkv, T, D] rope'd K/V into pool pages `page_ids` (device
    scatter; T padded to a whole number of pages by the caller).  With
    quantized pools pass the matching `scales` array: the chunks quantize
    per token into the pool's own dtype (int8 / fp8) and both arrays
    scatter TOGETHER in the same jitted program; returns (pages, scales).
    The page-and-scale atomicity here is what pool-quant-safe lint-proves
    on a live engine."""
    page = pages.shape[2]
    n = new.shape[2] // page
    # [n, Nkv, page, D] chunks in page order
    chunks = jnp.moveaxis(new[0], 1, 0).reshape(n, page, new.shape[1],
                                                new.shape[3])
    chunks = jnp.moveaxis(chunks, 2, 1)
    if scales is None:
        return pages.at[page_ids].set(chunks.astype(pages.dtype)), None
    q8, s = quantize_tokens(chunks, dtype=pages.dtype)
    return (pages.at[page_ids].set(q8),
            scales.at[page_ids].set(s))


def paged_prefill(params, tokens, state: PagedState, pool: PagePool,
                  slot: int, cfg: ModelConfig, mesh=None,
                  cache: Optional[PrefixCache] = None):
    """Absorb one prompt [T] into batch slot `slot`.

    Host-side wrapper: acquires ceil(T/page) pages, runs the jitted prompt
    pass (flash attention + paged K/V scatter), rewrites the slot's table
    row.  Returns (last-token logits [vocab] fp32, new PagedState); the
    acquired page ids are recorded in the returned state's table.

    `cache` (PrefixCache; bf16 or int8 pools — shared pages' dequant
    scales are pool state shared exactly like the K/V bytes): full pages whose
    token prefix is cached are REUSED — their K/V is never recomputed, the
    suffix runs a shorter prefill attending the cached context through an
    offset spec (_suffix_attention) — and this prompt's own full pages are
    registered for future requests.

    Tensor-parallel: pass the same `mesh` as paged_decode_step — the
    prompt's flash attention runs head-sharded through its own shard_map
    (_prompt_attention_dispatch) and the pool scatter follows the pools'
    sharding under GSPMD.
    """
    t = int(tokens.shape[0])
    page = state.k_pages[0].shape[2]
    max_pages = state.page_table.shape[1]
    n_need = -(-t // page)
    if n_need > max_pages:
        raise ValueError(f"prompt needs {n_need} pages > table width {max_pages}")
    if int(state.lengths[slot]) != 0:
        raise RuntimeError(
            f"slot {slot} is still live (len {int(state.lengths[slot])}); "
            "retire_slot first or its pages leak")
    if cache is not None:
        hashes = PrefixCache.chain(tokens, page, dtype=pool.dtype)
        # always leave >= 1 suffix token: the caller needs last-token logits
        hits = cache.lookup(hashes[: (t - 1) // page])
        if hits:
            t_pre = len(hits) * page
            suffix = tokens[t_pre:]
            t_suf = int(suffix.shape[0])
            n_suf = -(-t_suf // page)
            # page-multiple padding keeps the jit's compile key at page
            # granularity (varying prompt tails share one program); the
            # true length rides in as a traced scalar
            suffix = jnp.pad(suffix, (0, n_suf * page - t_suf))
            ids = []
            try:
                # inside the try: an exhausted-pool acquire must release
                # the lookup's hit references too, or they leak forever
                ids = pool.acquire(n_suf)
                logits, state = _paged_prefill_suffix_jit(
                    params, suffix[None, :], state,
                    jnp.asarray(hits, jnp.int32),
                    jnp.asarray(ids, jnp.int32), jnp.int32(slot),
                    jnp.int32(t_suf), cfg, t_pre, mesh)
            except Exception:
                pool.release(ids + hits)  # hits carry our lookup refs
                raise
            n_full = t // page
            # the FULL chain (hits included) so parent links are recorded
            cache.insert(hashes[:n_full],
                         hits + ids[: n_full - len(hits)])
            return logits[0], state
    ids = pool.acquire(n_need)
    try:
        logits, state = _paged_prefill_jit(
            params, tokens[None, :], state, jnp.asarray(ids, jnp.int32),
            jnp.int32(slot), cfg, mesh)
    except Exception:
        pool.release(ids)
        raise
    if cache is not None:
        cache.insert(hashes[: t // page], ids[: t // page])
    return logits[0], state


def _absorb_prompt(params, tokens, pos, state: PagedState, cfg,
                   layer_attn, layer_scatter):
    x = params["embed"].astype(cfg.dtype)[tokens]
    k_pools, v_pools, k_scs, v_scs = [], [], [], []
    for li, (p, kp, vp) in enumerate(zip(params["layers"], state.k_pages,
                                         state.v_pages)):
        q, k, v = _qkv_proj(p, x, pos, cfg)
        o = layer_attn(li, q, k, v)
        kp2, ks2, vp2, vs2 = layer_scatter(li, kp, vp, k, v)
        k_pools.append(kp2)
        v_pools.append(vp2)
        k_scs.append(ks2)
        v_scs.append(vs2)
        x = x + _attn_out(p, o)
        m, _ = _mlp(p, x, cfg, inference=True)
        x = x + m
    return _rms_norm(x, params["final_norm"]), k_pools, v_pools, k_scs, v_scs


def _write_table_row(state: PagedState, slot, row):
    return lax.dynamic_update_slice(
        state.page_table,
        jnp.pad(row, (0, state.page_table.shape[1] - row.shape[0]))[None, :],
        (slot, jnp.int32(0)),
    )


# `state` is donated (both prefill jits): serving deployments size the
# pools to fill HBM, so prefill must alias them in place — without donation
# every admission transiently needs 2x pool memory (old + new pools per
# layer) and a pool that fits would OOM on the first prompt.  The cost: if
# the jit fails at RUNTIME (post-donation), the caller's state is consumed
# and the release-and-reraise in paged_prefill only restores pool
# bookkeeping, not the state — trace/shape errors leave it retryable.
@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(2,))
def _paged_prefill_jit(params, tokens, state: PagedState, page_ids,
                       slot, cfg: ModelConfig, mesh=None):
    """slot is a TRACED int32 (one compile serves every slot); page_ids'
    static LENGTH keys the compile — one cache entry per prompt page count."""
    b, t = tokens.shape
    page = state.k_pages[0].shape[2]
    t_pad = -(-t // page) * page
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    quant = state.k_scales is not None

    def layer_attn(li, q, k, v):
        # attention consumes the full-precision K/V; only the POOL stores
        # the (possibly int8-quantized) copies
        return _prompt_attention_dispatch(q, k.astype(cfg.dtype),
                                          v.astype(cfg.dtype), cfg, mesh)

    def layer_scatter(li, kp, vp, k, v):
        pad = [(0, 0), (0, 0), (0, t_pad - t), (0, 0)]
        kp2, ks2 = _scatter_pages(
            kp, jnp.pad(k, pad), page_ids,
            state.k_scales[li] if quant else None)
        vp2, vs2 = _scatter_pages(
            vp, jnp.pad(v, pad), page_ids,
            state.v_scales[li] if quant else None)
        return kp2, ks2, vp2, vs2

    x, k_pools, v_pools, k_scs, v_scs = _absorb_prompt(
        params, tokens, pos, state, cfg, layer_attn, layer_scatter)
    logits = jnp.einsum("bsd,vd->bsv", x[:, -1:], params["lm_head"],
                        preferred_element_type=jnp.float32)[:, 0]
    table = _write_table_row(state, slot, page_ids)
    lengths = state.lengths.at[slot].set(t)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), table, lengths,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


# compile key: (cached-page count, suffix-page count) — the caller pads the
# suffix tokens to a page multiple and passes the true length as a TRACED
# scalar, so naturally varying prompt tails share one program
@partial(jax.jit, static_argnames=("cfg", "t_pre", "mesh"),
         donate_argnums=(2,))
def _paged_prefill_suffix_jit(params, tokens, state: PagedState, ctx_ids,
                              suf_ids, slot, t_suf, cfg: ModelConfig,
                              t_pre: int, mesh=None):
    """Prefill of a prompt whose first t_pre tokens' K/V already sit in
    cached pages (ctx_ids): compute q/k/v for the SUFFIX only (tokens is
    the suffix PADDED to a page multiple; t_suf the real length), attend
    the gathered cached context + suffix through one offset spec, scatter
    the suffix K/V into suf_ids, and point the slot's table row at
    [ctx_ids | suf_ids].  Shares the per-layer body (_absorb_prompt) with
    the full prefill."""
    b, t_pad = tokens.shape
    nkv, d_head = cfg.n_kv_heads, cfg.d_head
    quant = state.k_scales is not None
    pos = t_pre + jnp.broadcast_to(jnp.arange(t_pad, dtype=jnp.int32)[None],
                                   (b, t_pad))

    def layer_attn(li, q, k, v):
        # context dequantized through the shared gather (int8 shared pages'
        # scales are pool state, deterministic from token content — safe to
        # share across requests exactly like the K/V bytes); pad rows/cols
        # stay invisible through the traced q_hi/kv_hi bounds
        kc = _gather_dequant_pages(
            state.k_pages[li], state.k_scales[li] if quant else None,
            ctx_ids, nkv, d_head)[None]
        vc = _gather_dequant_pages(
            state.v_pages[li], state.v_scales[li] if quant else None,
            ctx_ids, nkv, d_head)[None]
        k_full = jnp.concatenate(
            [kc.astype(cfg.dtype), k.astype(cfg.dtype)], axis=2)
        v_full = jnp.concatenate(
            [vc.astype(cfg.dtype), v.astype(cfg.dtype)], axis=2)
        return _suffix_attention_dispatch(q, k_full, v_full, t_pre,
                                          q_hi=t_suf, kv_hi=t_pre + t_suf,
                                          cfg=cfg, mesh=mesh)

    def layer_scatter(li, kp, vp, k, v):
        kp2, ks2 = _scatter_pages(
            kp, k, suf_ids, state.k_scales[li] if quant else None)
        vp2, vs2 = _scatter_pages(
            vp, v, suf_ids, state.v_scales[li] if quant else None)
        return kp2, ks2, vp2, vs2

    x, k_pools, v_pools, k_scs, v_scs = _absorb_prompt(
        params, tokens, pos, state, cfg, layer_attn, layer_scatter)
    x_last = lax.dynamic_slice_in_dim(x, t_suf - 1, 1, axis=1)
    logits = jnp.einsum("bsd,vd->bsv", x_last, params["lm_head"],
                        preferred_element_type=jnp.float32)[:, 0]
    table = _write_table_row(state, slot, jnp.concatenate([ctx_ids, suf_ids]))
    lengths = state.lengths.at[slot].set(t_pre + t_suf)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), table, lengths,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(2,))
def paged_decode_step(params, tokens, state: PagedState, cfg: ModelConfig,
                      mesh=None):
    """One decode step for EVERY live slot (ragged batch).

    tokens: [slots] int32 — next input token per slot (ignored for empty
    slots).  Every live slot must have room for one more token in its last
    page... or its NEXT page already in the table row (see
    `ensure_capacity`).  Returns ([slots, vocab] fp32 logits, new state).
    `mesh` + cfg.head_axis: tensor-parallel serving — the page pools split
    over the head axis (see _paged_attention_dispatch).
    """
    slots = tokens.shape[0]
    page = state.k_pages[0].shape[2]
    live = state.lengths > 0
    pos = jnp.where(live, state.lengths, 0)  # next position = current length
    x = params["embed"].astype(cfg.dtype)[tokens[:, None]]  # [slots, 1, d]
    group = cfg.n_heads // cfg.n_kv_heads

    # which (page, offset) receives the new token per slot
    slot_page = state.lengths // page          # page slot index in table row
    offset = state.lengths % page
    page_id = jnp.take_along_axis(state.page_table, slot_page[:, None],
                                  axis=1)[:, 0]
    # dead slots write into the reserved sink page 0 (see PagePool) so their
    # mandatory scatter never collides with a live page
    # a LIVE slot mapping to page 0 means the caller skipped ensure_capacity
    # at an exact page boundary: the new token would scatter into the sink
    # and attention would read sink garbage — per-sequence silent corruption.
    # A jitted fn can't raise, so poison that slot's logits with NaN below.
    boundary_unassigned = live & (page_id == 0)
    page_id = jnp.where(live, page_id, 0)

    quant = state.k_scales is not None
    k_pools, v_pools, k_scs, v_scs = [], [], [], []
    for li, (p, kp, vp) in enumerate(zip(params["layers"], state.k_pages,
                                         state.v_pages)):
        q, k, v = _qkv_proj(p, x, pos[:, None], cfg)
        # append: scatter each slot's new K/V row into its page
        k_row, v_row = k[:, :, 0], v[:, :, 0]
        ks = vs = None
        if quant:
            k8, k_s = quantize_tokens(k_row, dtype=kp.dtype)
            v8, v_s = quantize_tokens(v_row, dtype=vp.dtype)
            kp = kp.at[page_id, :, offset].set(k8)
            vp = vp.at[page_id, :, offset].set(v8)
            ks = state.k_scales[li].at[page_id, :, offset].set(k_s)
            vs = state.v_scales[li].at[page_id, :, offset].set(v_s)
        else:
            kp = kp.at[page_id, :, offset].set(k_row.astype(kp.dtype))
            vp = vp.at[page_id, :, offset].set(v_row.astype(vp.dtype))
        qg = q.reshape(slots, cfg.n_kv_heads, group, cfg.d_head)
        o = _paged_attention_dispatch(
            qg, kp, vp, ks, vs, state.page_table,
            state.lengths + live.astype(jnp.int32), cfg, mesh)
        o = o.reshape(slots, cfg.n_heads, 1, cfg.d_head)
        x = x + _attn_out(p, o)
        m, _ = _mlp(p, x, cfg, inference=True)
        x = x + m
        k_pools.append(kp)
        v_pools.append(vp)
        k_scs.append(ks)
        v_scs.append(vs)
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)[:, 0]
    logits = jnp.where(boundary_unassigned[:, None], jnp.nan, logits)
    lengths = state.lengths + live.astype(jnp.int32)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), state.page_table, lengths,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def paged_multi_step(params, tokens, state: PagedState, cfg: ModelConfig):
    """Append T tokens to EVERY live slot in one pass (speculative
    verification / chunked decode): tokens [slots, T] -> ([slots, T,
    vocab] f32 logits, state with lengths += T for live slots).

    Attention dense-gathers each slot's pages (paged_decode_reference
    style): at speculative T (~4) the model matmuls dominate and the
    gather amortizes over T positions — the single-token hot path keeps
    the Pallas kernel.  The new tokens' K/V scatter into the pool FIRST,
    so the gathered context already contains them (no concat path).
    Capacity for all T tokens must be pre-assigned (provision_capacity);
    dead slots scatter into the sink page and emit garbage logits the
    caller ignores.  Speculative ROLLBACK is `rollback_tokens` — a pure
    lengths decrement, because entries past lengths are invisible; with
    int8 pools the rolled-back tokens' stale SCALES are equally invisible
    and the next append overwrites values and scales together."""
    quant = state.k_scales is not None
    slots, t = tokens.shape
    page = state.k_pages[0].shape[2]
    max_ctx = state.page_table.shape[1] * page
    group = cfg.n_heads // cfg.n_kv_heads
    live = state.lengths > 0
    base = jnp.where(live, state.lengths, 0)
    pos = base[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [slots,T]
    # per-token destination pages (sink for dead slots)
    slot_ix = jnp.arange(slots)[:, None]
    pids = state.page_table[slot_ix, pos // page]
    # a LIVE slot mapping any position to page 0 means the caller skipped
    # provision_capacity: poison that slot's logits (same loud-failure
    # contract as paged_decode_step) instead of silently scattering into
    # the sink page and attending garbage
    boundary_unassigned = live & jnp.any(pids == 0, axis=1)
    pids = jnp.where(live[:, None], pids, 0)
    offs = pos % page
    col = jnp.arange(max_ctx, dtype=jnp.int32)[None, :]           # [1, ctx]
    x = params["embed"].astype(cfg.dtype)[tokens]                 # [S,T,dm]
    k_pools, v_pools, k_scs, v_scs = [], [], [], []
    for li, (p, kp, vp) in enumerate(zip(params["layers"], state.k_pages,
                                         state.v_pages)):
        q, k, v = _qkv_proj(p, x, pos, cfg)
        # scatter new K/V: [slots, T, Nkv, D] at ([slots,T] pages, offsets)
        k_rows = jnp.moveaxis(k, 1, 2)
        v_rows = jnp.moveaxis(v, 1, 2)
        ks = vs = None
        if quant:
            k8, k_s = quantize_tokens(k_rows, dtype=kp.dtype)
            v8, v_s = quantize_tokens(v_rows, dtype=vp.dtype)
            kp = kp.at[pids, :, offs].set(k8)
            vp = vp.at[pids, :, offs].set(v8)
            ks = state.k_scales[li].at[pids, :, offs].set(k_s)
            vs = state.v_scales[li].at[pids, :, offs].set(v_s)
        else:
            kp = kp.at[pids, :, offs].set(k_rows.astype(kp.dtype))
            vp = vp.at[pids, :, offs].set(v_rows.astype(vp.dtype))

        # gather each slot's full context (now including the new tokens)
        kc = _gather_dequant_pages(kp, ks, state.page_table,
                                   cfg.n_kv_heads, cfg.d_head)
        vc = _gather_dequant_pages(vp, vs, state.page_table,
                                   cfg.n_kv_heads, cfg.d_head)
        qg = q.reshape(slots, cfg.n_kv_heads, group, t, cfg.d_head)
        s = jnp.einsum("bngtd,bnjd->bngtj", qg.astype(jnp.float32),
                       kc.astype(jnp.float32)) * cfg.d_head**-0.5
        visible = col[:, None, :] <= pos[:, :, None]              # causal
        if cfg.window is not None:
            visible &= col[:, None, :] > pos[:, :, None] - cfg.window
        s = jnp.where(visible[:, None, None, :, :], s, float("-inf"))
        o = jnp.einsum("bngtj,bnjd->bngtd", jax.nn.softmax(s, axis=-1),
                       vc.astype(jnp.float32))
        o = o.reshape(slots, cfg.n_heads, t, cfg.d_head).astype(cfg.dtype)
        x = x + _attn_out(p, o)
        m, _ = _mlp(p, x, cfg, inference=True)
        x = x + m
        k_pools.append(kp)
        v_pools.append(vp)
        k_scs.append(ks)
        v_scs.append(vs)
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("btd,vd->btv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    logits = jnp.where(boundary_unassigned[:, None, None], jnp.nan, logits)
    lengths = state.lengths + t * live.astype(jnp.int32)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), state.page_table, lengths,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


def rollback_tokens(state: PagedState, slot: int, n: int) -> PagedState:
    """Host-side: un-append the last n tokens of `slot` (speculative
    rejection).  Pure lengths bookkeeping — entries past lengths are
    invisible and the next append overwrites them; pages stay assigned."""
    length = int(state.lengths[slot])
    if n < 0 or n >= length:
        # n == length would zero the slot while its table row still owns
        # pages: retire_slot early-returns on length 0 and the pages leak
        raise ValueError(f"cannot roll back {n} of {length} tokens "
                         "(at least one must remain; retire_slot frees)")
    return state._replace(lengths=state.lengths.at[slot].set(length - n))


def ensure_capacity(state: PagedState, pool: PagePool, slot: int) -> PagedState:
    """Host-side: guarantee slot has a page for its next token, acquiring
    one if its last page is full.  Call before paged_decode_step."""
    length = int(state.lengths[slot])
    page = state.k_pages[0].shape[2]
    if length % page != 0 or length == 0:
        return state  # room in the current page (or empty slot)
    slot_page = length // page
    if slot_page >= state.page_table.shape[1]:
        raise RuntimeError(f"slot {slot} exceeded max_pages_per_seq")
    if int(state.page_table[slot, slot_page]) != 0:
        # idempotent: a prior (possibly aborted) pass already assigned the
        # page — page 0 is the reserved sink, so 0 reliably means unassigned
        return state
    (new_id,) = pool.acquire(1)
    table = state.page_table.at[slot, slot_page].set(new_id)
    return state._replace(page_table=table)


def provision_capacity(state: PagedState, pool: PagePool, slot: int,
                       n_tokens: int) -> PagedState:
    """Host-side: pre-assign every page `slot` needs to absorb `n_tokens`
    MORE tokens, so a decode loop of that many steps needs no further
    host-side allocation (one host fetch here vs one `ensure_capacity`
    length sync per slot per step in the hot loop)."""
    if n_tokens <= 0:
        return state
    length = int(state.lengths[slot])
    if length == 0:
        raise RuntimeError(
            f"slot {slot} is empty; paged_prefill acquires its own pages — "
            "provisioning now would leak them when prefill rewrites the row")
    page = state.k_pages[0].shape[2]
    last = length + n_tokens - 1  # final position to be written
    need_through = last // page   # highest table column required
    if need_through >= state.page_table.shape[1]:
        raise RuntimeError(
            f"slot {slot}: {n_tokens} more tokens need table column "
            f"{need_through} >= max_pages_per_seq {state.page_table.shape[1]}")
    row = np.asarray(state.page_table[slot])  # one fetch for all columns
    missing = [p for p in range(need_through + 1) if row[p] == 0]
    if not missing:
        return state
    ids = pool.acquire(len(missing))
    table = state.page_table.at[slot, np.asarray(missing)].set(
        np.asarray(ids, dtype=np.int32))
    return state._replace(page_table=table)


def retire_slot(state: PagedState, pool: PagePool, slot: int) -> PagedState:
    """Host-side: release a finished sequence's pages and empty the slot."""
    length = int(state.lengths[slot])
    if length == 0:
        return state
    # release EVERY assigned page in the row, used or pre-acquired
    # (ensure_capacity adds one ahead; provision_capacity may add many) —
    # page 0 is the unassigned sentinel, so non-zero means acquired.
    # Zero the row so a later ensure/provision on the re-prefilled slot
    # can't mistake stale ids for assignments.
    row = np.asarray(state.page_table[slot])
    ids = [int(i) for i in row if i != 0]
    pool.release(ids)
    return state._replace(
        lengths=state.lengths.at[slot].set(0),
        page_table=state.page_table.at[slot].set(0))
