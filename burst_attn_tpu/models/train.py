"""Training loop machinery for the flagship LM: mesh building, sharded state
init, and a jitted train step over a (dp, sp[, inter], tp) mesh.

This is the end-to-end integration layer the reference delegates to host
frameworks (BMTrain; reference README.md:36-38) — here it is in-framework and
TPU-native: one `jax.jit` whose input/output shardings come from the model's
PartitionSpec tree; XLA inserts the DP grad psums and megatron TP collectives,
while burst_attn's shard_map runs the sequence ring over `sp` (and the
hierarchical double ring when an `inter` axis is present).

Loss convention: next-token cross entropy.  `tokens` and `labels` arrive
already layout-permuted (parallel/layouts.to_layout on axis=1) with `labels`
shifted BEFORE the permutation — shifting after would cross shard boundaries.
`positions` carries true global positions for rotary (layouts.position_ids).
"""

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs

logger = obs.get_logger(__name__)

# -- train-loop metrics (host boundary: updated by guarded_step's wrapper,
# never inside the jitted step — burstlint `obs-jit-safe`).  Step time is
# measured dispatch-to-dispatch: the jitted step is async, so wall time
# between consecutive dispatches equals steady-state step time once the
# pipeline fills, WITHOUT inserting a device sync that would serialize the
# host-to-device prefetch against the running step (use
# obs.StepTimer/runner for blocking per-step times).
_M_STEPS = obs.counter("train.steps")
_M_EVENTS = obs.counter(
    "train.events", "exceptional train-loop events by kind (probe_failure; "
                    "loss-scale kinds reserved for a mixed-precision scaler)")
_M_STEP_S = obs.histogram("train.step_interval_s")
_M_TPS = obs.gauge("train.tokens_per_s")

from .transformer import ModelConfig, forward, forward_with_aux, init_params, param_specs
from ..parallel import layouts


@dataclass(frozen=True)
class TrainConfig:
    lr: float = 3e-4
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    moe_aux_weight: float = 0.01  # weight of the MoE load-balancing loss
    grad_accum: int = 1  # microbatches per optimizer step (scan inside jit)
    # Collect device-side ring telemetry (obs.devstats) every step: the
    # forward accumulates a DevStats pytree IN-GRAPH and guarded_step
    # publishes it into the obs registry after dispatch.  Diagnostic knob:
    # publishing reads the (tiny) stats arrays back each step, which
    # synchronizes the host with the step stream — leave off for
    # steady-state throughput runs (the train.step_interval_s
    # dispatch-interval histogram stays meaningful either way, the sync
    # happens after the interval is measured).
    collect_devstats: bool = False


def make_mesh(axis_sizes: dict, devices=None) -> Mesh:
    """Build a Mesh from {"dp": 2, "sp": 2, "tp": 2}-style sizes (order is
    significant: last axis is innermost = most ICI-local)."""
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(f"mesh {axis_sizes} needs {n} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n]).reshape(sizes), names)


def _optimizer(tcfg: TrainConfig):
    return optax.chain(
        optax.clip_by_global_norm(tcfg.grad_clip),
        optax.adamw(tcfg.lr, b1=tcfg.b1, b2=tcfg.b2, weight_decay=tcfg.weight_decay),
    )


def _state_specs(cfg: ModelConfig, tcfg: TrainConfig, params_shape):
    """PartitionSpec pytree for (params, opt_state): optimizer moments shard
    like their parameters.

    Matching is by TREE PATH, not array shape: optax state leaves embed the
    parameter tree, so an optimizer leaf whose path ends with a parameter's
    path (e.g. `.0.mu.layers[0].wq` vs `.layers[0].wq`) is that parameter's
    moment.  Shape-keyed matching would silently transpose specs whenever two
    differently-sharded parameters share a shape (w_gate/w_down at
    d_ff == d_model).  `params_shape` may be abstract (ShapeDtypeStructs).
    """
    pspecs = param_specs(cfg)
    opt = _optimizer(tcfg)
    opt_shape = jax.eval_shape(opt.init, params_shape)

    path_to_spec = {
        jax.tree_util.keystr(kp): spec
        for kp, spec in jax.tree_util.tree_flatten_with_path(
            pspecs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }

    def spec_of(kp, leaf):
        s = jax.tree_util.keystr(kp)
        for p, spec in path_to_spec.items():
            if s.endswith(p):
                return spec
        return P()  # scalars / step counts

    opt_specs = jax.tree_util.tree_map_with_path(spec_of, opt_shape)
    return pspecs, opt_specs


def init_train_state(key, cfg: ModelConfig, tcfg: TrainConfig, mesh: Mesh):
    """Initialize (params, opt_state) sharded over `mesh` per param_specs."""
    opt = _optimizer(tcfg)
    pspecs = param_specs(cfg)

    def init_fn(key):
        params = init_params(key, cfg)
        return params, opt.init(params)

    params_shape, opt_shape = jax.eval_shape(init_fn, key)
    _, opt_specs = _state_specs(cfg, tcfg, params_shape)
    out_shardings = (
        jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                     is_leaf=lambda x: isinstance(x, P)),
        jax.tree.map(lambda s: NamedSharding(mesh, s), opt_specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )
    return jax.jit(init_fn, out_shardings=out_shardings)(key)


def _loss_parts(params, tokens, positions, labels, cfg: ModelConfig, mesh,
                segment_ids=None, collect_stats=False):
    """(sum of masked nll, MoE aux[, DevStats]) — the linear pieces of the
    objective; `collect_stats` (static) appends the ring telemetry pytree."""
    out = forward_with_aux(params, tokens, positions, cfg, mesh,
                           segment_ids=segment_ids,
                           collect_stats=collect_stats)
    if collect_stats:
        logits, aux, stats = out
    else:
        logits, aux = out
    valid = labels >= 0
    labels_safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels_safe[..., None], axis=-1)[..., 0]
    nll_sum = jnp.sum(jnp.where(valid, nll, 0.0))
    if collect_stats:
        return nll_sum, aux, stats
    return nll_sum, aux


def loss_fn(params, tokens, positions, labels, cfg: ModelConfig, mesh,
            moe_aux_weight: float = 0.0, segment_ids=None):
    """Mean next-token cross entropy (fp32) + weighted MoE aux loss.
    labels < 0 are masked out."""
    nll_sum, aux = _loss_parts(params, tokens, positions, labels, cfg, mesh,
                               segment_ids=segment_ids)
    ce = nll_sum / jnp.maximum(jnp.sum(labels >= 0), 1)
    return ce + moe_aux_weight * aux


def packed_fields(tokens, eos_id: int):
    """Derive packed-training fields from a [B, S] token stream in NATURAL
    order, where documents are delimited by `eos_id` (the EOS token belongs
    to the document it ends — the usual packing convention):

      segment_ids [B, S]  document index per token (monotone from 0)
      positions   [B, S]  rotary positions restarting at each document
      labels      [B, S]  next-token targets, -1 at document ends (the EOS
                          token never predicts the next document's first
                          token) and at the final position

    Feed tokens/labels/segment_ids through layouts.to_layout(axis=1) before
    a zigzag/striped ring; positions are already true positions and ride
    the same permutation."""
    b, s = tokens.shape
    is_eos = tokens == eos_id
    # token t's segment = number of EOS strictly before t
    seg = jnp.cumsum(is_eos.astype(jnp.int32), axis=1) - is_eos.astype(jnp.int32)
    idx = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    is_start = jnp.concatenate(
        [jnp.ones((b, 1), bool), seg[:, 1:] != seg[:, :-1]], axis=1)
    seg_start = lax.associative_scan(jnp.maximum,
                                     jnp.where(is_start, idx, 0), axis=1)
    positions = idx - seg_start
    nxt_same = jnp.concatenate(
        [seg[:, 1:] == seg[:, :-1], jnp.zeros((b, 1), bool)], axis=1)
    labels = jnp.where(
        nxt_same,
        jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1),
        -1,
    )
    return seg, positions, labels


def packed_fields_np(tokens, eos_id: int):
    """numpy twin of packed_fields for the HOST prefetch path: the loader
    thread derives packed fields without touching the device (an eager jax
    derivation would block on a device round-trip per batch, serializing
    against the in-flight train step)."""
    tokens = np.asarray(tokens)
    b, s = tokens.shape
    is_eos = tokens == eos_id
    seg = (np.cumsum(is_eos, axis=1) - is_eos).astype(np.int32)
    idx = np.broadcast_to(np.arange(s, dtype=np.int32)[None], (b, s))
    is_start = np.concatenate(
        [np.ones((b, 1), bool), seg[:, 1:] != seg[:, :-1]], axis=1)
    seg_start = np.maximum.accumulate(np.where(is_start, idx, 0), axis=1)
    positions = (idx - seg_start).astype(np.int32)
    nxt_same = np.concatenate(
        [seg[:, 1:] == seg[:, :-1], np.zeros((b, 1), bool)], axis=1)
    labels = np.where(
        nxt_same,
        np.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1),
        -1,
    ).astype(np.int32)
    return seg, positions, labels


def probe_model_tri_bwd(cfg: ModelConfig, mesh: Mesh, batch=None, *,
                        seq_len: int = None, packed: bool = None):
    """Map a model/mesh onto the flash backward's per-shard kernel shapes
    and run the memoized tri-backward compile probe
    (ops/pallas_flash.ensure_tri_bwd).  Called automatically by
    make_train_step's first step; callable eagerly with explicit
    seq_len/packed (runner does, so the probe outcome prints before
    training starts).

    Returns None when this model can never compile the tri backward —
    jnp backend, windowed attention (banded kernels, not tri), or a
    non-TPU backend (interpret mode) — True/False for the probe outcome
    otherwise."""
    if batch is not None:
        seq_len = int(batch["tokens"].shape[1])
        if packed is None:
            packed = batch.get("segment_ids") is not None
    if cfg.attn_backend == "jnp" or cfg.window is not None or not cfg.causal:
        return None  # tri grids are causal-only; window takes the band path
    if jax.default_backend() != "tpu":
        return None  # pallas runs interpreted: nothing can fail Mosaic
    if cfg.attn_strategy == "ulysses":
        # all-to-all re-gathers the full sequence; heads split instead
        s_kernel = seq_len
    else:  # burst ring: each round's kernel sees the per-shard chunk
        ring = int(np.prod([mesh.shape.get(a, 1) for a in cfg.seq_axes]))
        s_kernel = seq_len // ring
    from ..ops.pallas_flash import ensure_tri_bwd

    return ensure_tri_bwd(
        s_kernel, cfg.d_head, n=cfg.n_heads, n_kv=cfg.n_kv_heads,
        segments=bool(packed), block_q=cfg.block_q, block_kv=cfg.block_kv)


def make_train_step(cfg: ModelConfig, tcfg: TrainConfig, mesh: Mesh):
    """Returns jitted step((params, opt_state), batch) -> (state, metrics).

    batch = dict(tokens, positions, labels), each [B, S] in layout order,
    sharded (dp, sp).
    """
    opt = _optimizer(tcfg)
    aux_w = tcfg.moe_aux_weight if cfg.n_experts else 0.0
    accum = tcfg.grad_accum
    collect = tcfg.collect_devstats
    if collect and accum != 1:
        raise ValueError(
            "collect_devstats supports grad_accum=1 only (per-microbatch "
            "stats inside the accumulation scan would need a scan-carried "
            "merge; fold it in when a run needs both)")

    def grad_of(params, batch):
        return jax.value_and_grad(loss_fn)(
            params, batch["tokens"], batch["positions"], batch["labels"], cfg,
            mesh, moe_aux_weight=aux_w,
            segment_ids=batch.get("segment_ids"),
        )

    def grad_of_stats(params, batch):
        # loss_fn's objective with the ring telemetry riding as has_aux;
        # gradients are bit-identical to grad_of (the stats custom_vjp
        # reuses the plain backward — burstlint devstats-pure)
        def scalar(params):
            nll_sum, aux, stats = _loss_parts(
                params, batch["tokens"], batch["positions"], batch["labels"],
                cfg, mesh, segment_ids=batch.get("segment_ids"),
                collect_stats=True)
            ce = nll_sum / jnp.maximum(jnp.sum(batch["labels"] >= 0), 1)
            return ce + aux_w * aux, stats

        (loss, stats), grads = jax.value_and_grad(scalar, has_aux=True)(params)
        return loss, stats, grads

    def step(state, batch):
        params, opt_state = state
        if collect:
            loss, devstats_out, grads = grad_of_stats(params, batch)
        elif accum == 1:
            loss, grads = grad_of(params, batch)
        else:
            b0 = batch["tokens"].shape[0]
            if b0 % accum:
                raise ValueError(f"batch {b0} not divisible by grad_accum {accum}")
            if cfg.batch_axis is not None:
                dp = mesh.shape.get(cfg.batch_axis, 1)
                if (b0 // accum) % dp:
                    raise ValueError(
                        f"microbatch {b0 // accum} (batch {b0} / grad_accum "
                        f"{accum}) not divisible by {cfg.batch_axis!r} mesh "
                        f"size {dp}")
            # split the batch dim into `accum` microbatches inside ONE jit —
            # large effective batch, constant memory.  The masked mean is
            # normalized by the GLOBAL valid count (known upfront from the
            # labels alone), so uneven masking across microbatches yields
            # exactly the full-batch objective: the aux term is folded into
            # each microbatch scalar with weight v_total/accum so one grad
            # accumulation covers both pieces.
            v_total = jnp.maximum(
                jnp.sum(batch["labels"] >= 0).astype(jnp.float32), 1.0)
            mb = jax.tree.map(
                lambda a: a.reshape(accum, a.shape[0] // accum, *a.shape[1:]),
                batch,
            )

            def micro_scalar(params, micro):
                nll_sum, aux = _loss_parts(
                    params, micro["tokens"], micro["positions"],
                    micro["labels"], cfg, mesh,
                    segment_ids=micro.get("segment_ids"))
                return nll_sum + aux_w * aux * (v_total / accum)

            def body(carry, micro):
                s_c, grads_c = carry
                s, grads = jax.value_and_grad(micro_scalar)(params, micro)
                return (s_c + s, jax.tree.map(jnp.add, grads_c, grads)), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (s_sum, grads), _ = jax.lax.scan(body, (jnp.float32(0.0), zeros), mb)
            loss = s_sum / v_total
            grads = jax.tree.map(lambda g: g / v_total, grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm}
        if collect:
            metrics["devstats"] = devstats_out
        return (params, opt_state), metrics

    jit_step = jax.jit(step, donate_argnums=(0,))
    probed = []
    last_dispatch = []  # [t_prev] once the first step has gone out

    def guarded_step(state, batch):
        # Default tri-backward probe (round-4 verdict #8): before the first
        # step's (much larger) jit compiles, ACTUALLY compile the
        # wrapped-diagonal fused backward this config would take, so a
        # Mosaic rejection on an untested TPU generation degrades to the
        # rectangular kernel (BURST_NO_TRI_BWD, see ops/pallas_flash.
        # probe_tri_bwd) instead of crashing the training step.  Memoized
        # process-wide (ensure_tri_bwd) — one compile per config, shared
        # with every other entry point.
        # The probe is a BEST-EFFORT guard: it must never be able to fail
        # training itself (a raise here would crash the first step, and a
        # retried step would silently skip the guard since `probed` is
        # already marked) — any failure degrades to running unprobed.
        if not probed:
            probed.append(True)
            try:
                probe_model_tri_bwd(cfg, mesh, batch)
            except Exception as e:  # noqa: BLE001
                _M_EVENTS.inc(kind="probe_failure")
                logger.warning(
                    "tri-backward compile probe failed (%s: %s); training "
                    "proceeds unprobed — a Mosaic rejection would now "
                    "surface from the first step's jit instead of "
                    "degrading to the rectangular kernel",
                    type(e).__name__, e)
        out = jit_step(state, batch)
        now = time.perf_counter()
        _M_STEPS.inc()
        if last_dispatch:
            dt = now - last_dispatch[0]
            _M_STEP_S.observe(dt)
            if dt > 0:
                # .size on a sharded array is the static GLOBAL element
                # count — no device sync
                _M_TPS.set(batch["tokens"].size / dt)
        last_dispatch[:] = [now]
        if collect:
            # fold the (tiny) device stats into the host registry AFTER the
            # dispatch interval is measured; publish reads the arrays back,
            # so this is the one host<->device sync the knob buys.  Best
            # effort: telemetry must never be able to fail a train step.
            new_state, metrics = out
            stats = metrics.pop("devstats")
            try:
                stats.publish(labels={"source": "train"})
            except Exception as e:  # noqa: BLE001
                _M_EVENTS.inc(kind="devstats_publish_failure")
                logger.warning("devstats publish failed (%s: %s); step "
                               "continues without telemetry",
                               type(e).__name__, e)
            out = (new_state, metrics)
        return out

    return guarded_step


def train_step(state, batch, cfg: ModelConfig, tcfg: TrainConfig, mesh: Mesh):
    """Convenience one-shot (compiles per call; prefer make_train_step)."""
    return make_train_step(cfg, tcfg, mesh)(state, batch)


def batch_from_host(tokens, labels, cfg: ModelConfig, mesh: Mesh,
                    packed_eos_id=None):
    """Turn a host batch (e.g. from data.DataLoader: inputs/targets
    [B, S] int32 numpy, natural order) into the sharded, layout-permuted
    batch dict `make_train_step` consumes.

    Labels are shifted by the LOADER (targets = window[1:]), so here they
    only get the same layout permutation as tokens.

    `packed_eos_id`: treat the stream as EOS-delimited packed documents —
    positions restart per document, labels are re-derived with boundary
    masking, and segment_ids join the batch (attention isolation via
    forward(..., segment_ids)).  The loader's shifted labels are superseded
    in this mode (packed_fields recomputes them from tokens alone).

    Multi-process: `tokens`/`labels` are each process's LOCAL batch (e.g.
    its shard of the DataLoader stream); the global batch is assembled
    across processes, so the global batch size is local_B x the number of
    batch-sharding processes.  A plain device_put of local data against a
    cross-host sharding would silently drop most loaded rows.
    """
    tokens = np.asarray(tokens)
    labels = np.asarray(labels)
    b, s = tokens.shape
    world = int(np.prod([mesh.shape.get(a, 1) for a in cfg.seq_axes]))
    perm = layouts.seq_permutation(cfg.layout, s, world)
    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    sharding = NamedSharding(mesh, P(cfg.batch_axis, seq_spec))
    if jax.process_count() > 1:
        put = partial(jax.make_array_from_process_local_data, sharding)
    else:
        put = partial(jax.device_put, device=sharding)
    if packed_eos_id is not None:
        seg, pos_packed, labels_packed = packed_fields_np(tokens, packed_eos_id)
        return {
            "tokens": put(np.ascontiguousarray(tokens[:, perm])),
            "positions": put(np.ascontiguousarray(pos_packed[:, perm])),
            "labels": put(np.ascontiguousarray(labels_packed[:, perm])),
            "segment_ids": put(np.ascontiguousarray(seg[:, perm])),
        }
    pos = np.ascontiguousarray(
        np.broadcast_to(np.asarray(perm, np.int32)[None, :], (b, s)))
    return {
        "tokens": put(np.ascontiguousarray(tokens[:, perm])),
        "positions": put(pos),
        "labels": put(np.ascontiguousarray(labels[:, perm])),
    }


def prefetch_batches(dl, cfg: ModelConfig, mesh: Mesh, depth: int = 2,
                     packed_eos_id=None):
    """Generator keeping `depth` device batches in flight: host->device
    transfer of batch N+1..N+depth overlaps the step running on batch N
    (device_put is async; the loader's worker threads fill the windows).
    `dl` is a data.DataLoader (or any (inputs, targets) iterator).
    `packed_eos_id`: see batch_from_host — packed-document training."""
    from collections import deque

    q = deque()
    it = iter(dl)
    mk = partial(batch_from_host, cfg=cfg, mesh=mesh,
                 packed_eos_id=packed_eos_id)
    try:
        for _ in range(depth):
            x, y = next(it)
            q.append(mk(x, y))
    except StopIteration:
        pass  # source shorter than depth
    else:
        for x, y in it:
            q.append(mk(x, y))
            yield q.popleft()
    while q:  # finite iterator: drain what is already in flight
        yield q.popleft()


def make_packed_batch(key, cfg: ModelConfig, mesh: Mesh, batch: int, seq: int,
                      eos_id: int = 0):
    """Synthetic PACKED LM batch: random tokens with EOS delimiters sprinkled
    in, fields derived by packed_fields, everything permuted into layout
    order and placed with (dp, sp) sharding."""
    world = int(np.prod([mesh.shape.get(a, 1) for a in cfg.seq_axes]))
    k1, k2 = jax.random.split(key)
    tokens = jax.random.randint(k1, (batch, seq), 0, cfg.vocab, dtype=jnp.int32)
    # ~4 documents per row on average
    eos_mask = jax.random.bernoulli(k2, 4.0 / seq, (batch, seq))
    tokens = jnp.where(eos_mask, eos_id, jnp.maximum(tokens, 1))
    seg, positions, labels = packed_fields(tokens, eos_id)
    to_l = lambda a: layouts.to_layout(a, cfg.layout, world, axis=1)
    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    sharding = NamedSharding(mesh, P(cfg.batch_axis, seq_spec))
    return {
        "tokens": jax.device_put(to_l(tokens), sharding),
        "positions": jax.device_put(to_l(positions), sharding),
        "labels": jax.device_put(to_l(labels), sharding),
        "segment_ids": jax.device_put(to_l(seg), sharding),
    }


def make_batch(key, cfg: ModelConfig, mesh: Mesh, batch: int, seq: int):
    """Synthetic LM batch in layout order, placed with (dp, sp) sharding."""
    world = int(np.prod([mesh.shape.get(a, 1) for a in cfg.seq_axes]))
    tokens = jax.random.randint(key, (batch, seq), 0, cfg.vocab, dtype=jnp.int32)
    labels = jnp.concatenate(
        [tokens[:, 1:], jnp.full((batch, 1), -1, jnp.int32)], axis=1
    )
    pos = jnp.asarray(layouts.seq_permutation(cfg.layout, seq, world), jnp.int32)
    positions = jnp.broadcast_to(pos[None, :], (batch, seq))
    tokens_l = layouts.to_layout(tokens, cfg.layout, world, axis=1)
    labels_l = layouts.to_layout(labels, cfg.layout, world, axis=1)
    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    sharding = NamedSharding(mesh, P(cfg.batch_axis, seq_spec))
    return {
        "tokens": jax.device_put(tokens_l, sharding),
        "positions": jax.device_put(positions, sharding),
        "labels": jax.device_put(labels_l, sharding),
    }
