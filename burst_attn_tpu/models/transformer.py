"""Flagship model: a decoder-only transformer LM on burst (ring) attention.

The reference is an op library whose integration story is "plug
burst_attn_func into your training framework" (reference README.md:36-38,
CPM-Live/BMTrain integration).  Here the model layer is first-class and
TPU-native: pure-functional pytree parameters with an explicit
PartitionSpec tree, so one `jit` with sharding constraints expresses
DP x TP x SP (sequence ring) over a named mesh — XLA inserts the
collectives (megatron-style TP from the param specs; the sequence ring
from burst_attn's shard_map).

Layout contract: `tokens` / `positions` fed to `forward` are in LAYOUT
order (parallel/layouts.to_layout) when causal load balancing is on;
`positions` carries the true global position of each token so rotary
embeddings are exact under any permutation (parallel/layouts.position_ids).

Design choices (TPU-first):
  * bf16 activations/params, fp32 rotary and norm accumulation, fp32 logits
    for a stable softmax cross-entropy.
  * RMSNorm + SwiGLU + rotary: the modern decoder block; all matmuls are
    [.., D] x [D, ..] einsums that XLA tiles onto the MXU.
  * GQA: n_kv_heads <= n_heads, both divisible by the tp axis size.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.burst import burst_attn
from ..utils.compat import shard_map


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32768
    d_model: int = 1024
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_head: int = 128
    d_ff: int = 2816  # ~8/3 * d_model rounded to 256
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # attention / parallelism
    causal: bool = True
    attn_strategy: str = "burst"  # "burst" (ring) | "ulysses" (all-to-all)
    layout: str = "zigzag"  # ring layouts; ulysses uses natural order
    attn_backend: str = "auto"
    # sliding-window causal attention (tokens each query may see, incl.
    # itself); requires layout="contig" — see parallel/burst.py
    window: Optional[int] = None
    seq_axes: Tuple[str, ...] = ("sp",)
    batch_axis: Optional[str] = "dp"
    head_axis: Optional[str] = "tp"
    # kernel blocks; None = per-TPU-generation defaults (ops/tuning.py),
    # clamped down for short shards
    block_q: Optional[int] = None
    block_kv: Optional[int] = None
    remat: bool = True  # jax.checkpoint each block: FLOPs for HBM
    # MoE (parallel/moe.py): n_experts=0 -> dense SwiGLU MLP.  With experts,
    # every layer's MLP becomes a top-k routed MoE; expert_axis names the
    # mesh axis experts shard over (GSPMD inserts the dispatch collectives)
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    expert_axis: Optional[str] = None
    # Pipeline parallelism (models/pipeline_lm.py): pp_axis names the mesh
    # axis stages shard over; layers are then stored STACKED [n_layers, ...]
    # (dim 0 sharded over pp) and the forward runs the GPipe schedule.
    # pp_microbatches must divide the per-dp-shard batch.
    pp_axis: Optional[str] = None
    pp_microbatches: int = 1


Params = Dict[str, Any]


def _split(key, n):
    return list(jax.random.split(key, n))


def init_params(key, cfg: ModelConfig) -> Params:
    """Initialize the parameter pytree (all leaves cfg.dtype except norms)."""
    d, nh, nkv, hd, f = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.d_head, cfg.d_ff
    init = jax.nn.initializers.normal(stddev=0.02)

    def dense(k, shape):
        return init(k, shape, cfg.dtype)

    keys = _split(key, cfg.n_layers + 2)
    layers = []
    for lk in keys[: cfg.n_layers]:
        ks = _split(lk, 6)
        layer = {
            "attn_norm": jnp.ones((d,), jnp.float32),
            "wq": dense(ks[0], (d, nh, hd)),
            "wk": dense(ks[1], (d, nkv, hd)),
            "wv": dense(ks[2], (d, nkv, hd)),
            "wo": dense(ks[3], (nh, hd, d)),
            "mlp_norm": jnp.ones((d,), jnp.float32),
        }
        if cfg.n_experts:
            from ..parallel.moe import init_moe_params

            layer.update(
                **init_moe_params(ks[4], d, f, cfg.n_experts,
                                  dtype=cfg.dtype)._asdict()
            )
        else:
            layer.update(
                w_gate=dense(ks[4], (d, f)),
                w_up=dense(ks[5], (d, f)),
                w_down=dense(_split(ks[5], 2)[1], (f, d)),
            )
        layers.append(layer)
    if cfg.pp_axis is not None:
        from .pipeline_lm import stack_layers

        layers = stack_layers(layers)
    return {
        "embed": init(keys[-2], (cfg.vocab, d), cfg.dtype),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": init(keys[-1], (cfg.vocab, d), cfg.dtype),
    }


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params: megatron TP over `head_axis`.

    qkv projections are column-parallel (heads sharded), the output
    projection row-parallel, the MLP gate/up column- and down row-parallel;
    embeddings/lm_head shard the vocab dim.  Norm scales are replicated.
    """
    tp = cfg.head_axis
    layer = {
        "attn_norm": P(None),
        "wq": P(None, tp, None),
        "wk": P(None, tp, None),
        "wv": P(None, tp, None),
        "wo": P(tp, None, None),
        "mlp_norm": P(None),
    }
    if cfg.n_experts:
        # experts shard over expert_axis ONLY (the _mlp shard_map slices the
        # same way); sharding their ffn dim over tp as well would need a
        # row-parallel psum inside the expert MLP — replication across tp is
        # the simpler trade at these expert sizes
        ep = cfg.expert_axis
        layer.update(
            router=P(None, None),
            w_gate=P(ep, None, None),
            w_up=P(ep, None, None),
            w_down=P(ep, None, None),
        )
    else:
        layer.update(
            w_gate=P(None, tp),
            w_up=P(None, tp),
            w_down=P(tp, None),
        )
    if cfg.pp_axis is not None:
        # stacked layout: leading stage/layer dim sharded over pp, with the
        # per-leaf tp axes PRESERVED in the trailing dims — pipeline_lm
        # passes these specs as shard_map in_specs, and its hand-written
        # megatron psums assume column/row-sliced weights (replicating them
        # here would double-count after the psums)
        layer = {k: P(cfg.pp_axis, *s) for k, s in layer.items()}
        return {
            "embed": P(None, None),
            "layers": layer,
            "final_norm": P(None),
            "lm_head": P(None, None),
        }
    return {
        "embed": P(tp, None),
        "layers": [layer] * cfg.n_layers,
        "final_norm": P(None),
        "lm_head": P(tp, None),
    }


def _rms_norm(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale).astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding. x [B, N, S, H], positions [B, S] (global token ids)."""
    h = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, h, 2, dtype=jnp.float32) / h))
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs  # [B,1,S,H/2]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _qkv_proj(p, x, positions, cfg: ModelConfig):
    """Norm + qkv projections + rotary — shared by the regular and
    pipeline-parallel paths (a numerics change here must hit both, or the
    pp-vs-regular parity tests break)."""
    h = _rms_norm(x, p["attn_norm"])
    q = jnp.einsum("bsd,dnh->bnsh", h, p["wq"])
    k = jnp.einsum("bsd,dnh->bnsh", h, p["wk"])
    v = jnp.einsum("bsd,dnh->bnsh", h, p["wv"])
    return (_rope(q, positions, cfg.rope_theta),
            _rope(k, positions, cfg.rope_theta), v)


def _attn_out(p, o):
    """Output projection (row-parallel under tp) — shared like _qkv_proj."""
    return jnp.einsum("bnsh,nhd->bsd", o, p["wo"])


def _attention(p, x, positions, cfg: ModelConfig, mesh, segment_ids=None,
               collect_stats=False):
    """One attention sublayer.  `collect_stats` (static) additionally
    returns the ring's in-graph DevStats (burst strategy only — ulysses has
    no ring to instrument): `(out, DevStats)` instead of `out`."""
    q, k, v = _qkv_proj(p, x, positions, cfg)
    if collect_stats and cfg.attn_strategy != "burst":
        raise ValueError(
            "collect_stats requires attn_strategy='burst' (devstats "
            f"instruments the ring); got {cfg.attn_strategy!r}")
    if cfg.attn_strategy == "ulysses":
        if len(cfg.seq_axes) != 1:
            raise ValueError("ulysses supports a single sequence axis")
        if cfg.layout != "contig":
            # ulysses attends in array order with a plain causal mask; a ring
            # layout permutation would silently scramble causality
            raise ValueError(
                "attn_strategy='ulysses' requires layout='contig' (natural "
                f"token order); got layout={cfg.layout!r}"
            )
        from ..parallel.ulysses import ulysses_attn

        o = ulysses_attn(
            q, k, v, mesh=mesh, seq_axis=cfg.seq_axes[0], causal=cfg.causal,
            backend=cfg.attn_backend, block_q=cfg.block_q,
            block_kv=cfg.block_kv, batch_axes=cfg.batch_axis,
            head_axes=cfg.head_axis, window=cfg.window,
            segment_ids=segment_ids,
        )
    elif cfg.attn_strategy == "burst":
        o = burst_attn(
            q,
            k,
            v,
            mesh=mesh,
            seq_axes=cfg.seq_axes,
            causal=cfg.causal,
            layout=cfg.layout,
            backend=cfg.attn_backend,
            block_q=cfg.block_q,
            block_kv=cfg.block_kv,
            batch_axes=cfg.batch_axis,
            head_axes=cfg.head_axis,
            window=cfg.window,
            segment_ids=segment_ids,
            collect_stats=collect_stats,
        )
        if collect_stats:
            o, stats = o
            return _attn_out(p, o), stats
    else:
        raise ValueError(
            f"unknown attn_strategy {cfg.attn_strategy!r}; "
            "expected 'burst' or 'ulysses'"
        )
    return _attn_out(p, o)


def _mlp(p, x, cfg: Optional[ModelConfig] = None, mesh=None, inference=False):
    """Dense SwiGLU, or (cfg.n_experts > 0) a routed MoE.  Returns
    (out, aux_loss) — aux is 0 for the dense path so callers are uniform.

    MoE routing is PER SHARD (GShard): tokens route within their
    (batch, seq)-shard's group, so the [T, E, C] dispatch tensors stay
    O(local_tokens^2) instead of O(global_tokens^2) — routing the global
    token set as one group is quadratically infeasible at long sequence.
    `inference=True` sizes capacity drop-free (tokens x top_k): silently
    zeroing a token's MLP output is a training-time trade, not an
    inference-time one.
    """
    h = _rms_norm(x, p["mlp_norm"])
    if cfg is not None and cfg.n_experts:
        from ..parallel.moe import MoEParams, moe_shard

        mp = MoEParams(p["router"], p["w_gate"], p["w_up"], p["w_down"])
        token_axes = tuple(
            a for a in (cfg.batch_axis, *cfg.seq_axes) if a is not None
        )
        # single-program callers (decode) have no mesh: no expert axis, no
        # cross-shard aux reduction
        ep_axis = cfg.expert_axis if mesh is not None else None
        # Drop-free inference routes in CHUNKS: capacity == chunk size is
        # drop-free (a token contributes at most one slot per expert), and
        # chunking keeps the [chunk, E, chunk] dispatch tensors O(chunk^2)
        # instead of O(T^2) on long prefills.  Chunking is exact when
        # nothing drops — routing is per-token.
        chunk = 512

        def route(mp, h2, cap):
            y, aux, _ = moe_shard(
                mp, h2, top_k=cfg.moe_top_k, capacity=cap, axis=ep_axis
            )
            return y, aux

        def group(mp, h):
            bb, ss, dd = h.shape
            tokens = bb * ss
            h2 = h.reshape(tokens, dd)
            if inference:
                c = min(chunk, tokens)
                if tokens % c or ep_axis is not None:
                    # ragged, or collectives in route (vmap of all_to_all is
                    # not supported): one drop-free group
                    y, aux = route(mp, h2, tokens)
                else:
                    yc, aux = jax.vmap(lambda hc: route(mp, hc, c))(
                        h2.reshape(tokens // c, c, dd)
                    )
                    y, aux = yc.reshape(tokens, dd), jnp.mean(aux)
            else:
                from ..parallel.moe import capacity_for

                cap = capacity_for(tokens, cfg.n_experts, cfg.moe_top_k,
                                   cfg.moe_capacity_factor)
                y, aux = route(mp, h2, cap)
            # moe_shard pmeans over the expert axis; average the remaining
            # token-sharding axes so aux is replicated
            rest = tuple(a for a in token_axes if a != ep_axis)
            if mesh is not None and rest:
                aux = jax.lax.pmean(aux, rest)
            return y.reshape(bb, ss, dd), aux

        if mesh is None:  # single-program path (e.g. decode off-mesh)
            y, aux = group(mp, h)
            return y, aux

        seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
        ep = cfg.expert_axis
        if ep is not None:
            ep_size = mesh.shape.get(ep, 1)
            if cfg.n_experts % ep_size:
                raise ValueError(
                    f"n_experts {cfg.n_experts} not divisible by "
                    f"expert_axis {ep!r} size {ep_size}")
        pspec = MoEParams(P(None, None), P(ep, None, None),
                          P(ep, None, None), P(ep, None, None))
        y, aux = shard_map(
            group, mesh=mesh,
            in_specs=(pspec, P(cfg.batch_axis, seq_spec, None)),
            out_specs=(P(cfg.batch_axis, seq_spec, None), P()),
            check_vma=False,
        )(mp, h)
        return y, aux
    gate = jnp.einsum("bsd,df->bsf", h, p["w_gate"])
    up = jnp.einsum("bsd,df->bsf", h, p["w_up"])
    out = jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, p["w_down"])
    return out, jnp.float32(0.0)


def forward(params: Params, tokens, positions, cfg: ModelConfig, mesh,
            segment_ids=None) -> jax.Array:
    """tokens, positions: [B, S] int32 (layout order). Returns fp32 logits
    [B, S, vocab].  segment_ids [B, S]: packed-sequence ids in layout order
    (attention never crosses document boundaries)."""
    logits, _ = forward_with_aux(params, tokens, positions, cfg, mesh,
                                 segment_ids=segment_ids)
    return logits


def forward_with_aux(params: Params, tokens, positions, cfg: ModelConfig, mesh,
                     segment_ids=None, collect_stats=False):
    """forward + the summed MoE auxiliary load-balancing loss (0 for dense
    models); the trainer adds `moe_aux_weight * aux` to the objective.

    `collect_stats` (static): additionally return the per-device ring
    telemetry folded across layers (obs.devstats.merge — counts add,
    extrema max/min) as a third element: `(logits, aux, DevStats)`.  Burst
    attention only; the pipeline-parallel path keeps its own schedule and
    does not thread stats."""
    if cfg.pp_axis is not None:
        if collect_stats:
            raise ValueError(
                "collect_stats is not supported on the pipeline-parallel "
                "path (pp_axis set) — the pp schedule slices layers across "
                "stages and has no single ring to instrument")
        from .pipeline_lm import pp_forward_with_aux

        return pp_forward_with_aux(params, tokens, positions, cfg, mesh,
                                   segment_ids=segment_ids)
    from jax.sharding import NamedSharding

    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    act_spec = NamedSharding(mesh, P(cfg.batch_axis, seq_spec, None))
    logit_spec = NamedSharding(mesh, P(cfg.batch_axis, seq_spec, cfg.head_axis))

    x = params["embed"].astype(cfg.dtype)[tokens]
    x = jax.lax.with_sharding_constraint(x, act_spec)

    def block(carry, p):
        if collect_stats:
            from ..obs import devstats

            x, aux, stats = carry
            a, st = _attention(p, x, positions, cfg, mesh,
                               segment_ids=segment_ids, collect_stats=True)
            x = x + a
            stats = st if stats is None else devstats.merge(stats, st)
        else:
            x, aux = carry
            x = x + _attention(p, x, positions, cfg, mesh,
                               segment_ids=segment_ids)
        m, aux_l = _mlp(p, x, cfg, mesh)
        x = jax.lax.with_sharding_constraint(x + m, act_spec)
        if collect_stats:
            return x, aux + aux_l, stats
        return x, aux + aux_l

    carry = ((x, jnp.float32(0.0), None) if collect_stats
             else (x, jnp.float32(0.0)))
    for p in params["layers"]:
        if cfg.remat:
            carry = jax.checkpoint(block)(carry, p)
        else:
            carry = block(carry, p)
    if collect_stats:
        x, aux, stats = carry
    else:
        x, aux = carry

    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "bsd,vd->bsv", x, params["lm_head"], preferred_element_type=jnp.float32
    )
    logits = jax.lax.with_sharding_constraint(logits, logit_spec)
    if collect_stats:
        return logits, aux, stats
    return logits, aux
