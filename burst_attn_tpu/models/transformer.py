"""Flagship model: a decoder-only transformer LM on burst (ring) attention.

The reference is an op library whose integration story is "plug
burst_attn_func into your training framework" (reference README.md:36-38,
CPM-Live/BMTrain integration).  Here the model layer is first-class and
TPU-native: pure-functional pytree parameters with an explicit
PartitionSpec tree, so one `jit` with sharding constraints expresses
DP x TP x SP (sequence ring) over a named mesh — XLA inserts the
collectives (megatron-style TP from the param specs; the sequence ring
from burst_attn's shard_map).

Layout contract: `tokens` / `positions` fed to `forward` are in LAYOUT
order (parallel/layouts.to_layout) when causal load balancing is on;
`positions` carries the true global position of each token so rotary
embeddings are exact under any permutation (parallel/layouts.position_ids).

Design choices (TPU-first):
  * bf16 activations/params, fp32 rotary and norm accumulation, fp32 logits
    for a stable softmax cross-entropy.
  * RMSNorm + SwiGLU + rotary: the modern decoder block; all matmuls are
    [.., D] x [D, ..] einsums that XLA tiles onto the MXU.
  * GQA: n_kv_heads <= n_heads, both divisible by the tp axis size.
"""

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.burst import burst_attn


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32768
    d_model: int = 1024
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_head: int = 128
    d_ff: int = 2816  # ~8/3 * d_model rounded to 256
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # attention / parallelism
    causal: bool = True
    attn_strategy: str = "burst"  # "burst" (ring) | "ulysses" (all-to-all)
    layout: str = "zigzag"  # ring layouts; ulysses uses natural order
    attn_backend: str = "auto"
    seq_axes: Tuple[str, ...] = ("sp",)
    batch_axis: Optional[str] = "dp"
    head_axis: Optional[str] = "tp"
    block_q: int = 2048  # kernel blocks, clamped down for short shards
    block_kv: int = 2048
    remat: bool = True  # jax.checkpoint each block: FLOPs for HBM


Params = Dict[str, Any]


def _split(key, n):
    return list(jax.random.split(key, n))


def init_params(key, cfg: ModelConfig) -> Params:
    """Initialize the parameter pytree (all leaves cfg.dtype except norms)."""
    d, nh, nkv, hd, f = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.d_head, cfg.d_ff
    init = jax.nn.initializers.normal(stddev=0.02)

    def dense(k, shape):
        return init(k, shape, cfg.dtype)

    keys = _split(key, cfg.n_layers + 2)
    layers = []
    for lk in keys[: cfg.n_layers]:
        ks = _split(lk, 6)
        layers.append(
            {
                "attn_norm": jnp.ones((d,), jnp.float32),
                "wq": dense(ks[0], (d, nh, hd)),
                "wk": dense(ks[1], (d, nkv, hd)),
                "wv": dense(ks[2], (d, nkv, hd)),
                "wo": dense(ks[3], (nh, hd, d)),
                "mlp_norm": jnp.ones((d,), jnp.float32),
                "w_gate": dense(ks[4], (d, f)),
                "w_up": dense(ks[5], (d, f)),
                "w_down": dense(_split(ks[5], 2)[1], (f, d)),
            }
        )
    return {
        "embed": init(keys[-2], (cfg.vocab, d), cfg.dtype),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": init(keys[-1], (cfg.vocab, d), cfg.dtype),
    }


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params: megatron TP over `head_axis`.

    qkv projections are column-parallel (heads sharded), the output
    projection row-parallel, the MLP gate/up column- and down row-parallel;
    embeddings/lm_head shard the vocab dim.  Norm scales are replicated.
    """
    tp = cfg.head_axis
    layer = {
        "attn_norm": P(None),
        "wq": P(None, tp, None),
        "wk": P(None, tp, None),
        "wv": P(None, tp, None),
        "wo": P(tp, None, None),
        "mlp_norm": P(None),
        "w_gate": P(None, tp),
        "w_up": P(None, tp),
        "w_down": P(tp, None),
    }
    return {
        "embed": P(tp, None),
        "layers": [layer] * cfg.n_layers,
        "final_norm": P(None),
        "lm_head": P(tp, None),
    }


def _rms_norm(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale).astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding. x [B, N, S, H], positions [B, S] (global token ids)."""
    h = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, h, 2, dtype=jnp.float32) / h))
    angles = positions[:, None, :, None].astype(jnp.float32) * freqs  # [B,1,S,H/2]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _attention(p, x, positions, cfg: ModelConfig, mesh):
    h = _rms_norm(x, p["attn_norm"])
    q = jnp.einsum("bsd,dnh->bnsh", h, p["wq"])
    k = jnp.einsum("bsd,dnh->bnsh", h, p["wk"])
    v = jnp.einsum("bsd,dnh->bnsh", h, p["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    if cfg.attn_strategy == "ulysses":
        if len(cfg.seq_axes) != 1:
            raise ValueError("ulysses supports a single sequence axis")
        if cfg.layout != "contig":
            # ulysses attends in array order with a plain causal mask; a ring
            # layout permutation would silently scramble causality
            raise ValueError(
                "attn_strategy='ulysses' requires layout='contig' (natural "
                f"token order); got layout={cfg.layout!r}"
            )
        from ..parallel.ulysses import ulysses_attn

        o = ulysses_attn(
            q, k, v, mesh=mesh, seq_axis=cfg.seq_axes[0], causal=cfg.causal,
            backend=cfg.attn_backend, block_q=cfg.block_q,
            block_kv=cfg.block_kv, batch_axes=cfg.batch_axis,
            head_axes=cfg.head_axis,
        )
    elif cfg.attn_strategy == "burst":
        o = burst_attn(
            q,
            k,
            v,
            mesh=mesh,
            seq_axes=cfg.seq_axes,
            causal=cfg.causal,
            layout=cfg.layout,
            backend=cfg.attn_backend,
            block_q=cfg.block_q,
            block_kv=cfg.block_kv,
            batch_axes=cfg.batch_axis,
            head_axes=cfg.head_axis,
        )
    else:
        raise ValueError(
            f"unknown attn_strategy {cfg.attn_strategy!r}; "
            "expected 'burst' or 'ulysses'"
        )
    return jnp.einsum("bnsh,nhd->bsd", o, p["wo"])


def _mlp(p, x):
    h = _rms_norm(x, p["mlp_norm"])
    gate = jnp.einsum("bsd,df->bsf", h, p["w_gate"])
    up = jnp.einsum("bsd,df->bsf", h, p["w_up"])
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, p["w_down"])


def forward(params: Params, tokens, positions, cfg: ModelConfig, mesh) -> jax.Array:
    """tokens, positions: [B, S] int32 (layout order). Returns fp32 logits
    [B, S, vocab]."""
    from jax.sharding import NamedSharding

    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    act_spec = NamedSharding(mesh, P(cfg.batch_axis, seq_spec, None))
    logit_spec = NamedSharding(mesh, P(cfg.batch_axis, seq_spec, cfg.head_axis))

    x = params["embed"].astype(cfg.dtype)[tokens]
    x = jax.lax.with_sharding_constraint(x, act_spec)

    def block(x, p):
        x = x + _attention(p, x, positions, cfg, mesh)
        x = x + _mlp(p, x)
        return jax.lax.with_sharding_constraint(x, act_spec)

    for p in params["layers"]:
        if cfg.remat:
            x = jax.checkpoint(block)(x, p)
        else:
            x = block(x, p)

    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "bsd,vd->bsv", x, params["lm_head"], preferred_element_type=jnp.float32
    )
    return jax.lax.with_sharding_constraint(logits, logit_spec)
