"""Speculative decoding (draft-verify) over the dense KV cache.

A small DRAFT model proposes k tokens autoregressively; the TARGET model
scores all k+1 positions in ONE cached forward pass (`forward_cached`
already handles multi-token appends) and keeps the longest prefix of
proposals that matches its own greedy choice, plus one token of its own
(the correction at the first mismatch, or the bonus after k acceptances).
Output is TOKEN-EXACT with plain greedy decoding of the target — the
draft only changes how many target forward passes are needed, never what
they produce (verified by test).

Cache bookkeeping is the TPU-friendly part: `Cache.length` is the only
rollback state — K/V written past it are invisible (the visibility mask
keys on length) and are simply overwritten by the next append, so
rejecting proposals costs a scalar, not a buffer copy.

Greedy only (`temperature == 0`): stochastic acceptance (Leviathan-style
p/q rejection sampling) changes the acceptance rule, not the cache
machinery, and is left as a documented seam.

Reference parity: none — the reference has no decoding stack at all.
"""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .decode import Cache, forward_cached, prefill
from .transformer import ModelConfig


class SpecStats(NamedTuple):
    proposed: int      # draft tokens proposed
    accepted: int      # draft tokens accepted by the target
    target_passes: int  # target forward passes (vs `steps` for plain decode)


def _greedy(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _feed(params, cache: Cache, tokens, cfg: ModelConfig):
    """Append T tokens (1-D) to the cache; returns ([T, vocab] logits,
    cache).  Positions derive from the cache length (scalar device add —
    no host sync).  Jitted: one program per token-count (T=1 for drafts'
    catch-up, T=kk+1 for verification — bounded by k+1 shapes total)."""
    t = tokens.shape[0]
    positions = cache.length + jnp.arange(t, dtype=jnp.int32)
    logits, cache = forward_cached(params, tokens[None], positions[None],
                                   cache, cfg)
    return logits[0], cache


# cache donated in both jits: the old cache is never reused after a call,
# and an undonated input forces XLA to copy every layer's [B,Nkv,max_seq,D]
# buffer per call (2x peak cache memory + a full HBM round-trip per round)
@partial(jax.jit, static_argnames=("cfg", "kk"), donate_argnums=(1,))
def _draft_propose(params, cache: Cache, last, cfg: ModelConfig, kk: int):
    """kk greedy draft steps as ONE compiled lax.scan — no per-token
    dispatch or host sync.  Returns ([kk] proposed tokens, cache)."""

    def body(carry, _):
        cache, tok = carry
        positions = cache.length[None, None]
        logits, cache = forward_cached(params, tok[None], positions, cfg=cfg,
                                       cache=cache)
        nxt = _greedy(logits[0, -1:])
        return (cache, nxt), nxt[0]

    (cache, _), toks = jax.lax.scan(body, (cache, last), None, length=kk)
    return toks, cache


def _rollback(cache: Cache, length) -> Cache:
    # +0 forces a FRESH buffer: both caches may be rolled back to the same
    # traced scalar (jnp.int32 of an int32 array is a no-op returning the
    # SAME object), and the donating jits would then delete one cache's
    # length out from under the other
    return cache._replace(length=jnp.asarray(length, jnp.int32) + 0)


def speculative_generate(params_target, params_draft, prompt,
                         cfg_target: ModelConfig, cfg_draft: ModelConfig,
                         *, steps: int, k: int = 4, max_seq: int,
                         return_stats: bool = False):
    """Greedy speculative decode.  prompt [1, T] int32; returns [steps]
    generated tokens (and SpecStats with return_stats=True).

    The draft and target must share a vocabulary; everything else
    (depth, width, GQA, attention backend) may differ.
    """
    if cfg_target.vocab != cfg_draft.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if prompt.shape[0] != 1:
        raise ValueError("speculative decode is single-sequence (B=1)")
    if prompt.shape[1] + steps + k + 1 > max_seq:
        raise ValueError("prompt + steps + k + 1 exceeds max_seq")

    logits_t, cache_t = prefill(params_target, prompt, cfg_target, max_seq)
    _, cache_d = prefill(params_draft, prompt, cfg_draft, max_seq)

    out = [int(_greedy(logits_t[0, -1]))]
    # invariant: each cache holds K/V for prompt + out[:-1]; out[-1] is the
    # newest token, not yet fed to either model
    proposed = accepted = 0
    target_passes = 0
    while len(out) < steps:
        kk = min(k, steps - len(out))
        # fresh buffer (+0): cache_t.length itself is donated away by _feed
        base_t = cache_t.length + 0
        # --- draft proposes kk tokens (one compiled scan, zero syncs) ---
        last = jnp.asarray([out[-1]], jnp.int32)
        draft_toks, cache_d = _draft_propose(params_draft, cache_d, last,
                                             cfg_draft, kk)
        proposed += kk
        # --- target scores all kk+1 positions in one pass ---
        feed = jnp.concatenate([last, draft_toks])
        lg_t, cache_t = _feed(params_target, cache_t, feed, cfg_target)
        target_passes += 1
        # the round's single host sync: proposals + target choices together
        drafts = [int(x) for x in np.asarray(draft_toks)]
        choice = np.asarray(_greedy(lg_t))  # [kk+1] target greedy tokens
        n_acc = 0
        while n_acc < kk and drafts[n_acc] == int(choice[n_acc]):
            n_acc += 1
        accepted += n_acc
        out += drafts[:n_acc]
        out.append(int(choice[n_acc]))  # correction or bonus
        # --- roll both caches back to prompt + out[:-1] ---
        new_len = base_t + n_acc + 1
        cache_t = _rollback(cache_t, new_len)
        if n_acc == kk:
            # all accepted: the draft (which fed out[-2] + drafts[:-1]) is
            # one token BEHIND the invariant — feed the last proposal
            _, cache_d = _feed(
                params_draft, cache_d, jnp.asarray([drafts[-1]], jnp.int32),
                cfg_draft)
        else:
            # rejected tail: the draft ran AHEAD; a scalar rollback
            # discards it (stale K/V past length are invisible)
            cache_d = _rollback(cache_d, new_len)
    tokens = np.asarray(out[:steps], np.int32)
    if return_stats:
        return tokens, SpecStats(proposed, accepted, target_passes)
    return tokens
