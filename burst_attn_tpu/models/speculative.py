"""Speculative decoding (draft-verify) over the dense KV cache.

A small DRAFT model proposes k tokens autoregressively; the TARGET model
scores all k+1 positions in ONE cached forward pass (`forward_cached`
already handles multi-token appends) and keeps the longest prefix of
proposals that matches its own greedy choice, plus one token of its own
(the correction at the first mismatch, or the bonus after k acceptances).
Output is TOKEN-EXACT with plain greedy decoding of the target — the
draft only changes how many target forward passes are needed, never what
they produce (verified by test).

Cache bookkeeping is the TPU-friendly part: `Cache.length` is the only
rollback state — K/V written past it are invisible (the visibility mask
keys on length) and are simply overwritten by the next append, so
rejecting proposals costs a scalar, not a buffer copy.

Two acceptance rules share the cache machinery:

* greedy (`temperature == 0`): accept while the proposal equals the
  target's argmax — token-exact with plain greedy target decoding.
* stochastic (`temperature > 0`): Leviathan-style rejection sampling —
  accept proposal x with probability min(1, p(x)/q(x)) (p = target, q =
  draft distribution at that position); on rejection, sample from the
  residual normalize(max(p - q, 0)).  The OUTPUT DISTRIBUTION equals
  sampling the target directly (`_residual_accept` is property-tested
  against exact enumeration), for any draft.

Reference parity: none — the reference has no decoding stack at all.
"""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .decode import Cache, forward_cached, prefill
from .transformer import ModelConfig


class SpecStats(NamedTuple):
    proposed: int      # draft tokens proposed
    accepted: int      # draft tokens accepted by the target
    target_passes: int  # target forward passes (vs `steps` for plain decode)


def _greedy(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _feed(params, cache: Cache, tokens, cfg: ModelConfig):
    """Append T tokens (1-D) to the cache; returns ([T, vocab] logits,
    cache).  Positions derive from the cache length (scalar device add —
    no host sync).  Jitted: one program per token-count (T=1 for drafts'
    catch-up, T=kk+1 for verification — bounded by k+1 shapes total)."""
    t = tokens.shape[0]
    positions = cache.length + jnp.arange(t, dtype=jnp.int32)
    logits, cache = forward_cached(params, tokens[None], positions[None],
                                   cache, cfg)
    return logits[0], cache


# cache donated in both jits: the old cache is never reused after a call,
# and an undonated input forces XLA to copy every layer's [B,Nkv,max_seq,D]
# buffer per call (2x peak cache memory + a full HBM round-trip per round)
@partial(jax.jit, static_argnames=("cfg", "kk", "temperature"),
         donate_argnums=(1,))
def _draft_propose(params, cache: Cache, last, key, cfg: ModelConfig,
                   kk: int, temperature: float):
    """kk draft steps as ONE compiled lax.scan — no per-token dispatch or
    host sync.  temperature == 0: greedy (q output is a placeholder);
    else: sampled, with each position's full f32 proposal distribution q
    (the acceptance rule needs p/q ratios — q MUST be computed in f32
    like the target side, or bf16 models bias the ratios and break the
    distribution-exactness guarantee).  Returns (tokens [kk], q [kk, V],
    cache, key)."""

    def body(carry, _):
        cache, tok, key = carry
        positions = cache.length[None, None]
        logits, cache = forward_cached(params, tok[None], positions, cfg=cfg,
                                       cache=cache)
        row = logits[0, -1].astype(jnp.float32)
        if temperature > 0.0:
            row = row / temperature
            key, ks = jax.random.split(key)
            nxt = jax.random.categorical(ks, row)[None].astype(jnp.int32)
            q = jax.nn.softmax(row)
        else:
            nxt = _greedy(row[None])
            q = row  # unused by the greedy acceptance rule
        return (cache, nxt, key), (nxt[0], q)

    (cache, _, key), (toks, qs) = jax.lax.scan(
        body, (cache, last, key), None, length=kk)
    return toks, qs, cache, key


def _temperature_probs(logits, temperature):
    return jax.nn.softmax(logits.astype(jnp.float32) / temperature, axis=-1)


def _residual_accept(p_rows, q_rows, drafts, key):
    """Leviathan acceptance on the host side of the round boundary.

    p_rows [kk+1, V] target probs, q_rows [kk, V] draft probs, drafts
    [kk] proposed tokens.  Returns (n_acc, next_token, key): proposals
    accept while u < p(x)/q(x); the first rejection samples the residual
    normalize(max(p - q, 0)); after kk acceptances the bonus token
    samples p_rows[kk].  Produces EXACTLY the target distribution per
    position (the classic telescoping argument), any draft.

    All randomness is drawn in ONE device call (kk+1 uniforms: one per
    accept test plus one for the residual/bonus sample) and the rows
    pulled in ONE transfer each; the per-token loop is pure numpy —
    per-position device round-trips would cost the very latency
    speculation amortizes."""
    kk = len(drafts)
    key, ku = jax.random.split(key)
    u = np.asarray(jax.random.uniform(ku, (kk + 1,)))
    p = np.asarray(p_rows, np.float64)
    q = np.asarray(q_rows, np.float64)

    def inv_cdf(probs, x):  # sample via one uniform, pure numpy
        c = np.cumsum(probs)
        return int(np.searchsorted(c, x * c[-1], side="right").clip(
            0, len(probs) - 1))

    for i in range(kk):
        x = int(drafts[i])
        if u[i] < p[i, x] / max(q[i, x], 1e-30):
            continue
        resid = np.maximum(p[i] - q[i], 0.0)
        if resid.sum() <= 0.0:
            # p <= q everywhere yet x rejected: numerically degenerate
            # (p == q); fall back to sampling the target row directly
            resid = p[i]
        return i, inv_cdf(resid, u[kk]), key
    return kk, inv_cdf(p[kk], u[kk]), key


def _rollback(cache: Cache, length) -> Cache:
    # +0 forces a FRESH buffer: both caches may be rolled back to the same
    # traced scalar (jnp.int32 of an int32 array is a no-op returning the
    # SAME object), and the donating jits would then delete one cache's
    # length out from under the other
    return cache._replace(length=jnp.asarray(length, jnp.int32) + 0)


def speculative_generate(params_target, params_draft, prompt,
                         cfg_target: ModelConfig, cfg_draft: ModelConfig,
                         *, steps: int, k: int = 4, max_seq: int,
                         temperature: float = 0.0, rng=None,
                         return_stats: bool = False):
    """Speculative decode.  prompt [1, T] int32; returns [steps] generated
    tokens (and SpecStats with return_stats=True).  temperature == 0 is
    greedy (token-exact with generate()); temperature > 0 samples with
    the Leviathan acceptance rule (output distribution == sampling the
    target directly).

    The draft and target must share a vocabulary; everything else
    (depth, width, GQA, attention backend) may differ.
    """
    if cfg_target.vocab != cfg_draft.vocab:
        raise ValueError("draft and target must share a vocabulary")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if prompt.shape[0] != 1:
        raise ValueError("speculative decode is single-sequence (B=1)")
    if prompt.shape[1] + steps + k + 1 > max_seq:
        raise ValueError("prompt + steps + k + 1 exceeds max_seq")
    sampled = temperature > 0.0
    key = rng if rng is not None else jax.random.PRNGKey(0)

    logits_t, cache_t = prefill(params_target, prompt, cfg_target, max_seq)
    _, cache_d = prefill(params_draft, prompt, cfg_draft, max_seq)

    if sampled:
        key, k0 = jax.random.split(key)
        out = [int(jax.random.categorical(k0, logits_t[0, -1] / temperature))]
    else:
        out = [int(_greedy(logits_t[0, -1]))]
    # invariant: each cache holds K/V for prompt + out[:-1]; out[-1] is the
    # newest token, not yet fed to either model
    proposed = accepted = 0
    target_passes = 0
    while len(out) < steps:
        kk = min(k, steps - len(out))
        # fresh buffer (+0): cache_t.length itself is donated away by _feed
        base_t = cache_t.length + 0
        # --- draft proposes kk tokens (one compiled scan, zero syncs) ---
        last = jnp.asarray([out[-1]], jnp.int32)
        key, kd = jax.random.split(key)
        draft_toks, q_rows, cache_d, _ = _draft_propose(
            params_draft, cache_d, last, kd, cfg_draft, kk, temperature)
        proposed += kk
        # --- target scores all kk+1 positions in one pass ---
        feed = jnp.concatenate([last, draft_toks])
        lg_t, cache_t = _feed(params_target, cache_t, feed, cfg_target)
        target_passes += 1
        # the round's single bulk host sync: proposals + target rows
        drafts = [int(x) for x in np.asarray(draft_toks)]
        if sampled:
            p_rows = _temperature_probs(lg_t, temperature)
            n_acc, nxt, key = _residual_accept(p_rows, q_rows, drafts, key)
        else:
            choice = np.asarray(_greedy(lg_t))  # [kk+1] target greedy tokens
            n_acc = 0
            while n_acc < kk and drafts[n_acc] == int(choice[n_acc]):
                n_acc += 1
            nxt = int(choice[n_acc])  # correction or bonus
        accepted += n_acc
        out += drafts[:n_acc]
        out.append(nxt)
        # --- roll both caches back to prompt + out[:-1] ---
        new_len = base_t + n_acc + 1
        cache_t = _rollback(cache_t, new_len)
        if n_acc == kk:
            # all accepted: the draft (which fed out[-2] + drafts[:-1]) is
            # one token BEHIND the invariant — feed the last proposal
            _, cache_d = _feed(
                params_draft, cache_d, jnp.asarray([drafts[-1]], jnp.int32),
                cfg_draft)
        else:
            # rejected tail: the draft ran AHEAD; a scalar rollback
            # discards it (stale K/V past length are invisible)
            cache_d = _rollback(cache_d, new_len)
    tokens = np.asarray(out[:steps], np.int32)
    if return_stats:
        return tokens, SpecStats(proposed, accepted, target_passes)
    return tokens
