"""End-to-end training runner + CLI: the glue that makes the framework a
trainer, not an op library.

Ties together the subsystems the reference delegates to host frameworks
(reference README.md:36-38): the native data loader (data/loader.py), the
sharded train step (models/train.py), orbax checkpointing
(utils/checkpoint.py), step timing + metrics (burst_attn_tpu.obs), and
rank-0 logging (utils/log_helper.py; handlers via the obs logger).  Resume is exact: the checkpoint step repositions the
deterministic loader with `seek(step)`, so the token stream continues as if
the run never stopped.

CLI:
    python -m burst_attn_tpu.models.runner --data tokens.batd --steps 100 \
        --mesh dp=2,sp=2,tp=2 --d-model 256 --n-layers 2 --seq-len 1024
"""

import argparse
import json
import time
from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np

from .train import (
    TrainConfig, batch_from_host, init_train_state, make_mesh, make_train_step,
    prefetch_batches, probe_model_tri_bwd,
)
from .transformer import ModelConfig
from .. import obs
from ..data import DataLoader
from ..obs import StepTimer, get_logger
from ..utils import log_helper


@dataclass(frozen=True)
class RunConfig:
    """One training run: data, duration, checkpointing cadence."""

    data_path: str
    steps: int
    batch: int
    seq_len: int
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 500
    log_every: int = 10
    seed: int = 0
    loader_threads: int = 2
    eval_data_path: Optional[str] = None
    eval_every: int = 500
    eval_batches: int = 16
    # packed-document training: EOS token id delimiting documents in the
    # token stream (None = plain contiguous LM crops)
    packed_eos_id: Optional[int] = None


def fit(cfg: ModelConfig, tcfg: TrainConfig, run: RunConfig, mesh):
    """Train for run.steps, checkpointing and resuming as configured.

    Returns (state, history) where history is a list of {step, loss, ...}
    dicts (rank-0 view).
    """
    log = get_logger("runner")
    primary = log_helper.is_primary()
    ckpt = None
    state, start_step = None, 0
    if run.ckpt_dir:
        from ..utils.checkpoint import Checkpointer

        ckpt = Checkpointer(run.ckpt_dir)
        state, restored = ckpt.restore_latest(cfg, tcfg, mesh)
        if restored is not None:
            start_step = restored
            if primary:
                log.info("resumed from step %d", start_step)
    if state is None:
        state = init_train_state(jax.random.PRNGKey(run.seed), cfg, tcfg, mesh)

    step_fn = make_train_step(cfg, tcfg, mesh)
    timer = StepTimer()
    history = []

    evaluator = None
    if run.eval_data_path:
        from .evaluate import Evaluator

        evaluator = Evaluator(
            cfg, mesh, run.eval_data_path, batch=run.batch,
            seq_len=run.seq_len, max_batches=run.eval_batches,
            packed_eos_id=run.packed_eos_id,
        )

    def maybe_eval(step):
        if evaluator is None:
            return
        if (step + 1) % run.eval_every and step + 1 != run.steps:
            return
        with obs.span("train.eval", step=step + 1):
            metrics = evaluator(state[0])
        row = {"step": step + 1, **{k: round(v, 4) for k, v in metrics.items()}}
        history.append(row)
        if primary:
            log.info("%s", json.dumps(row))
    try:
        with DataLoader(
            run.data_path, run.batch, run.seq_len,
            shard_id=jax.process_index(), num_shards=jax.process_count(),
            seed=run.seed, num_threads=run.loader_threads,
        ) as dl:
            if start_step:
                dl.seek(start_step)
            batches = prefetch_batches(dl, cfg, mesh,
                                       packed_eos_id=run.packed_eos_id)
            for step in range(start_step, run.steps):
                batch = next(batches)
                with timer as t:
                    state, metrics = step_fn(state, batch)
                    t.watch(state)
                if (step + 1) % run.log_every == 0 or step + 1 == run.steps:
                    row = {
                        "step": step + 1,
                        "loss": float(metrics["loss"]),
                        "grad_norm": float(metrics["grad_norm"]),
                        "step_s": timer.times[-1],
                    }
                    history.append(row)
                    if primary:
                        log.info("%s", json.dumps(row))
                maybe_eval(step)
                if ckpt and ((step + 1) % run.ckpt_every == 0 or step + 1 == run.steps):
                    ckpt.save(step + 1, state)
    finally:
        # flush the async orbax save even on an exception mid-run — the
        # crash case is exactly when the newest checkpoint matters
        if ckpt:
            ckpt.close()
        if evaluator is not None:
            evaluator.close()
    s = timer.summary()
    if s["steps"] and primary:
        log.info("done: %d steps, mean %.3fs/step", s["steps"], s["mean_s"])
    # BURST_OBS_EXPORT=<path>: drop the run's full metric/span state as an
    # obs JSONL export (readable with `python -m burst_attn_tpu.obs`)
    import os

    export_path = os.environ.get("BURST_OBS_EXPORT")
    if export_path:
        obs.export_jsonl(export_path)
        if primary:
            log.info("obs export written to %s", export_path)
    return state, history


def _parse_mesh(spec: str) -> dict:
    """"dp=2,sp=2,tp=2" -> {"dp": 2, "sp": 2, "tp": 2} (order preserved)."""
    out = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        if not size:
            raise ValueError(f"bad mesh spec {spec!r}; want e.g. dp=2,sp=4")
        out[name.strip()] = int(size)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description="Train the flagship LM on a token file.")
    p.add_argument("--data", required=True, help="BATD token file (data.write_token_file)")
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--mesh", default="sp=1", help="e.g. dp=2,sp=2,tp=2")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--ckpt-every", type=int, default=500)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--eval-data", default=None,
                   help="held-out BATD token file (perplexity eval)")
    p.add_argument("--eval-every", type=int, default=500)
    p.add_argument("--eval-batches", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--d-model", type=int, default=1024)
    p.add_argument("--n-layers", type=int, default=8)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-kv-heads", type=int, default=None)
    p.add_argument("--d-ff", type=int, default=None)
    p.add_argument("--layout", default="zigzag")
    p.add_argument("--n-experts", type=int, default=0,
                   help="MoE experts per layer (0 = dense MLP)")
    p.add_argument("--microbatches", type=int, default=None,
                   help="GPipe microbatches for a pp= mesh (default: pp size)")
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--packed-eos", type=int, default=None,
                   help="EOS token id delimiting packed documents: positions "
                        "restart per document, loss masks boundaries, and "
                        "attention never crosses them (segment_ids)")
    p.add_argument("--multihost", action="store_true",
                   help="call multihost.initialize() before touching jax")
    p.add_argument("--probe-tri-bwd", action="store_true", default=True,
                   help="(default ON) before building the train step, "
                        "actually COMPILE the wrapped-diagonal fused "
                        "backward at this run's per-shard sequence length; "
                        "if Mosaic rejects it (possible on generations "
                        "without a measured block table) fall back to the "
                        "rectangular kernel instead of crashing the full "
                        "train-step compile (costs one extra kernel compile "
                        "at startup, memoized process-wide)")
    p.add_argument("--no-probe-tri-bwd", dest="probe_tri_bwd",
                   action="store_false",
                   help="skip the startup tri-backward compile probe (the "
                        "first train step still runs it via make_train_step)")
    args = p.parse_args(argv)

    if args.multihost:
        from ..utils import multihost

        multihost.initialize()

    mesh_axes = _parse_mesh(args.mesh)
    # a double-ring mesh (inter, intra) maps straight onto seq_axes; any
    # other mesh uses a (possibly trivial) "sp" ring — auto-append sp=1 so
    # e.g. --mesh dp=8 works instead of dying on a missing axis
    if "inter" in mesh_axes and "intra" in mesh_axes:
        seq_axes = ("inter", "intra")
    else:
        seq_axes = ("sp",)
        mesh_axes.setdefault("sp", 1)
    mesh = make_mesh(mesh_axes)
    n_heads = args.n_heads
    # experts shard over a dedicated "ep" axis when the mesh has one, else
    # ride the dp axis (the classic GShard data+expert layout)
    expert_axis = None
    if args.n_experts:
        expert_axis = "ep" if "ep" in mesh_axes else (
            "dp" if "dp" in mesh_axes else None)
    # a pp= axis turns on the pipeline-parallel forward (pipeline_lm.py);
    # microbatches default to the stage count (the GPipe sweet spot floor)
    pp_axis = "pp" if "pp" in mesh_axes else None
    if args.microbatches and not pp_axis:
        raise SystemExit("--microbatches requires a pp= axis in --mesh")
    cfg = ModelConfig(
        seq_axes=seq_axes,
        batch_axis="dp" if "dp" in mesh_axes else None,
        head_axis="tp" if "tp" in mesh_axes else None,
        pp_axis=pp_axis,
        pp_microbatches=(args.microbatches or mesh_axes.get("pp", 1))
        if pp_axis else 1,
        n_experts=args.n_experts,
        expert_axis=expert_axis,
        vocab=args.vocab,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=n_heads,
        n_kv_heads=args.n_kv_heads or n_heads,
        d_head=args.d_model // n_heads,
        d_ff=args.d_ff or 4 * args.d_model,
        layout=args.layout,
        remat=not args.no_remat,
    )
    if args.probe_tri_bwd:
        # memoized (ensure_tri_bwd): make_train_step's first-step probe
        # then hits this result for free — running it eagerly here only
        # moves the one compile before startup so the outcome prints.
        # probe_model_tri_bwd owns the model-to-kernel shape mapping (ring
        # division, packed segment variant, jnp/window/non-TPU gates) so
        # this probes exactly the kernel the train step will take.
        ok = probe_model_tri_bwd(cfg, mesh, seq_len=args.seq_len,
                                 packed=args.packed_eos is not None)
        if ok is not None:
            print(f"probe_tri_bwd(seq={args.seq_len}, d={cfg.d_head}, "
                  f"gqa={cfg.n_heads != cfg.n_kv_heads}, "
                  f"packed={args.packed_eos is not None}): "
                  f"{'tri' if ok else 'RECT FALLBACK'}")
    tcfg = TrainConfig(lr=args.lr, grad_accum=args.grad_accum)
    run = RunConfig(
        data_path=args.data, steps=args.steps, batch=args.batch,
        seq_len=args.seq_len, ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every, log_every=args.log_every, seed=args.seed,
        eval_data_path=args.eval_data, eval_every=args.eval_every,
        eval_batches=args.eval_batches, packed_eos_id=args.packed_eos,
    )
    fit(cfg, tcfg, run, mesh)


if __name__ == "__main__":
    main()
