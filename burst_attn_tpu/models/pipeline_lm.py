"""Pipeline-parallel forward for the flagship LM: pp x dp x sp composed.

The reference has no pipeline parallelism (SURVEY.md §2.4 — DP/TP/PP are
delegated to host frameworks); `parallel/pipeline.py` provides the generic
GPipe-over-`lax.scan` building block, and this module is its integration
with the transformer + burst sequence ring (round-1 verdict item 5).

Composition problem: the regular forward path (transformer.forward_with_aux)
is GSPMD-style — einsums under jit with sharding constraints — and
`burst_attn` internally opens its own `shard_map` over the sequence axis.
`shard_map` does not nest, so a pipeline wrapper around that path can't
work.  TPU-native answer: ONE `shard_map` over the FULL (pp, dp, sp) mesh
whose body is fully manual per-shard code —

  * GPipe tick loop: stage p holds layers [p*L/P, (p+1)*L/P); activations
    `lax.ppermute` one hop along `pp` per tick; stage 0 injects microbatch
    t, the last stage banks finished microbatches (same schedule as
    parallel/pipeline.py:pipeline_shard).
  * attention: `burst_attn_shard` — the shard-level custom_vjp ring — runs
    over `sp` inside each stage (double ring over ("inter","intra") seq
    axes works the same way).
  * dp needs no code: the batch dim is sharded by the outer shard_map and
    parameter cotangents are psum'd across replicated axes by shard_map's
    transpose.

The backward pipeline schedule is free: jax.grad of scan + ppermute IS the
reverse schedule (ppermute transposes to the reverse permutation).

Restrictions (explicit errors below): no tensor parallelism (head_axis) and
no MoE inside the pp path — both would need hand-written megatron/dispatch
collectives in the manual body; compose them with dp/sp instead.

Parameter layout: `layers` holds stacked leaves [n_layers, ...] (dim 0
sharded over `pp`), not the regular list-of-dicts — see
transformer.init_params / stack_layers.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.burst import BurstConfig, burst_attn_shard, _resolve_backend
# the pure math MUST be shared with the regular path: a numerics change
# there must not silently break pp=1 vs pp=N parity (_mlp's dense path is
# per-shard pure math too — cfg=None selects it)
from .transformer import _mlp, _rms_norm, _rope


def stack_layers(layers):
    """List-of-layer-dicts -> one pytree with a leading [n_layers, ...] axis
    (the layout the pp path shards over the `pp` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked, n_layers):
    """Inverse of stack_layers (e.g. to run a pp checkpoint without pp)."""
    return [jax.tree.map(lambda a: a[i], stacked) for i in range(n_layers)]


def _layer_fwd(p, x, positions, cfg, bcfg: BurstConfig):
    """One transformer block, per-shard (x [mb, s_local, d]): local einsums
    + the burst ring over the sequence axes."""
    h = _rms_norm(x, p["attn_norm"])
    q = jnp.einsum("bsd,dnh->bnsh", h, p["wq"])
    k = jnp.einsum("bsd,dnh->bnsh", h, p["wk"])
    v = jnp.einsum("bsd,dnh->bnsh", h, p["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = burst_attn_shard(q, k, v, bcfg)
    x = x + jnp.einsum("bnsh,nhd->bsd", o, p["wo"])
    return x + _mlp(p, x)[0]


def _pp_forward_shard(layers_p, embed, final_norm, lm_head, tokens, positions,
                      *, cfg, bcfg: BurstConfig, m: int):
    """Per-shard body: embed -> GPipe ticks over `pp` -> head.

    layers_p: this stage's layers, leaves [L/P, ...]; tokens/positions
    [b_local, s_local] (dp x sp shard)."""
    pp = cfg.pp_axis
    n_stages = lax.axis_size(pp)
    stage = lax.axis_index(pp)
    b_l, s_l = tokens.shape
    x = embed.astype(cfg.dtype)[tokens]
    d = x.shape[-1]
    mb = b_l // m
    x_mb = x.reshape(m, mb, s_l, d)
    pos_mb = positions.reshape(m, mb, s_l)

    def stage_fn(x, pos):
        def body(x, p):
            return _layer_fwd(p, x, pos, cfg, bcfg), None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, layers_p)
        return x

    ticks = m + n_stages - 1
    buf = jnp.zeros_like(x_mb[0])  # activation arriving from the left
    out = jnp.zeros_like(x_mb)     # banked results (last stage only)

    def tick(carry, t):
        buf, out = carry
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m - 1), axis=0, keepdims=False)
        cur = jnp.where(stage == 0, inject, buf)
        # the activation at stage s on tick t is microbatch t - s; its
        # positions (rope) must travel with it.  Clamped: bubble ticks
        # compute garbage that is never banked.
        pos = lax.dynamic_index_in_dim(
            pos_mb, jnp.clip(t - stage, 0, m - 1), axis=0, keepdims=False)
        y = stage_fn(cur, pos)
        out_id = t - (n_stages - 1)
        bank = (stage == n_stages - 1) & (out_id >= 0)
        banked = lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(out_id, 0, m - 1), axis=0)
        out = jnp.where(bank, banked, out)
        nxt = lax.ppermute(
            y, pp, [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (nxt, out), None

    (_, out), _ = lax.scan(tick, (buf, out), jnp.arange(ticks))
    # banked outputs live on the last stage; psum replicates them so every
    # pp shard computes the (cheap) head on its own dp x sp shard
    xf = lax.psum(out, pp).reshape(b_l, s_l, d)
    xf = _rms_norm(xf, final_norm)
    return jnp.einsum("bsd,vd->bsv", xf, lm_head,
                      preferred_element_type=jnp.float32)


def pp_forward_with_aux(params, tokens, positions, cfg, mesh):
    """Pipeline-parallel forward_with_aux: fp32 logits [B, S, vocab], aux=0.

    Same contract as transformer.forward_with_aux; dispatched from there
    when cfg.pp_axis is set."""
    if cfg.head_axis is not None:
        raise ValueError(
            "pipeline parallelism does not compose with tensor parallelism "
            "(head_axis); use pp x dp x sp")
    if cfg.n_experts:
        raise ValueError("pipeline parallelism does not compose with MoE")
    if cfg.attn_strategy != "burst":
        raise ValueError("pp path supports attn_strategy='burst' only")
    n_stages = mesh.shape[cfg.pp_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by pp={n_stages}")
    m = cfg.pp_microbatches
    dp = mesh.shape[cfg.batch_axis] if cfg.batch_axis else 1
    b_local = tokens.shape[0] // dp
    if b_local % m:
        raise ValueError(
            f"per-dp-shard batch {b_local} not divisible by "
            f"pp_microbatches {m}")

    if len(cfg.seq_axes) == 1:
        inter_axis, intra_axis = None, cfg.seq_axes[0]
    else:
        inter_axis, intra_axis = cfg.seq_axes
    bcfg = BurstConfig(
        causal=cfg.causal,
        layout=cfg.layout,
        intra_axis=intra_axis,
        inter_axis=inter_axis,
        backend=_resolve_backend(cfg.attn_backend),
        block_q=cfg.block_q,
        block_kv=cfg.block_kv,
    )
    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    tok_spec = P(cfg.batch_axis, seq_spec)
    fn = jax.shard_map(
        partial(_pp_forward_shard, cfg=cfg, bcfg=bcfg, m=m),
        mesh=mesh,
        in_specs=(P(cfg.pp_axis), P(), P(), P(), tok_spec, tok_spec),
        out_specs=P(cfg.batch_axis, seq_spec, None),
        check_vma=False,
    )
    logits = fn(params["layers"], params["embed"], params["final_norm"],
                params["lm_head"], tokens, positions)
    return logits, jnp.float32(0.0)
