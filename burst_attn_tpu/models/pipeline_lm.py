"""Pipeline-parallel forward for the flagship LM: pp x dp x sp composed.

The reference has no pipeline parallelism (SURVEY.md §2.4 — DP/TP/PP are
delegated to host frameworks); `parallel/pipeline.py` provides the generic
GPipe-over-`lax.scan` building block, and this module is its integration
with the transformer + burst sequence ring (round-1 verdict item 5).

Composition problem: the regular forward path (transformer.forward_with_aux)
is GSPMD-style — einsums under jit with sharding constraints — and
`burst_attn` internally opens its own `shard_map` over the sequence axis.
`shard_map` does not nest, so a pipeline wrapper around that path can't
work.  TPU-native answer: ONE `shard_map` over the FULL (pp, dp, sp) mesh
whose body is fully manual per-shard code —

  * GPipe tick loop: stage p holds layers [p*L/P, (p+1)*L/P); activations
    `lax.ppermute` one hop along `pp` per tick; stage 0 injects microbatch
    t, the last stage banks finished microbatches (same schedule as
    parallel/pipeline.py:pipeline_shard).
  * attention: `burst_attn_shard` — the shard-level custom_vjp ring — runs
    over `sp` inside each stage (double ring over ("inter","intra") seq
    axes works the same way).
  * dp needs no code: the batch dim is sharded by the outer shard_map and
    parameter cotangents are psum'd across replicated axes by shard_map's
    transpose.

The backward pipeline schedule is free: jax.grad of scan + ppermute IS the
reverse schedule (ppermute transposes to the reverse permutation).

Tensor parallelism composes too: the megatron collectives GSPMD would infer
for the regular path are hand-written in `_layer_fwd` (column-sliced
qkv/gate/up, row-sliced wo/down, one psum over `tp` after each of attention
and the MLP).  Embeddings/lm_head stay replicated in pp mode (vocab-dim
sharding would need a masked-lookup + psum in the manual body for marginal
memory win).  MoE inside the pp path is still excluded (explicit error) —
its expert dispatch is the one remaining hand-written collective.

Parameter layout: `layers` holds stacked leaves [n_layers, ...] (dim 0
sharded over `pp`), not the regular list-of-dicts — see
transformer.init_params / stack_layers.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.burst import BurstConfig, burst_attn_shard, _resolve_backend
# the pure math MUST be shared with the regular path: a numerics change
# there must not silently break pp=1 vs pp=N parity (_mlp's dense path is
# per-shard pure math too — cfg=None selects it)
from .transformer import _mlp, _rms_norm, _rope, param_specs


def stack_layers(layers):
    """List-of-layer-dicts -> one pytree with a leading [n_layers, ...] axis
    (the layout the pp path shards over the `pp` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked, n_layers):
    """Inverse of stack_layers (e.g. to run a pp checkpoint without pp)."""
    return [jax.tree.map(lambda a: a[i], stacked) for i in range(n_layers)]


def _layer_fwd(p, x, positions, cfg, bcfg: BurstConfig):
    """One transformer block, per-shard (x [mb, s_local, d]).

    Tensor parallelism is hand-written megatron: qkv/gate/up weights arrive
    column-sliced over `tp` (so the einsums run on the local head/ffn
    shard), wo/down row-sliced, and the two psums below reduce the partial
    outputs — exactly the collectives GSPMD infers for the regular path's
    param_specs, made explicit because this body is inside shard_map."""
    tp = cfg.head_axis
    h = _rms_norm(x, p["attn_norm"])
    q = jnp.einsum("bsd,dnh->bnsh", h, p["wq"])
    k = jnp.einsum("bsd,dnh->bnsh", h, p["wk"])
    v = jnp.einsum("bsd,dnh->bnsh", h, p["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = burst_attn_shard(q, k, v, bcfg)
    attn = jnp.einsum("bnsh,nhd->bsd", o, p["wo"])
    if tp is not None:
        attn = lax.psum(attn, tp)
    x = x + attn
    mlp_out = _mlp(p, x)[0]
    if tp is not None:
        mlp_out = lax.psum(mlp_out, tp)
    return x + mlp_out


def _pp_forward_shard(layers_p, embed, final_norm, lm_head, tokens, positions,
                      *, cfg, bcfg: BurstConfig, m: int):
    """Per-shard body: embed -> GPipe ticks over `pp` -> head.

    layers_p: this stage's layers, leaves [L/P, ...]; tokens/positions
    [b_local, s_local] (dp x sp shard)."""
    pp = cfg.pp_axis
    n_stages = lax.axis_size(pp)
    stage = lax.axis_index(pp)
    b_l, s_l = tokens.shape
    x = embed.astype(cfg.dtype)[tokens]
    d = x.shape[-1]
    mb = b_l // m
    x_mb = x.reshape(m, mb, s_l, d)
    pos_mb = positions.reshape(m, mb, s_l)

    def stage_fn(x, pos):
        def body(x, p):
            return _layer_fwd(p, x, pos, cfg, bcfg), None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, layers_p)
        return x

    ticks = m + n_stages - 1
    buf = jnp.zeros_like(x_mb[0])  # activation arriving from the left
    out = jnp.zeros_like(x_mb)     # banked results (last stage only)

    def tick(carry, t):
        buf, out = carry
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m - 1), axis=0, keepdims=False)
        cur = jnp.where(stage == 0, inject, buf)
        # the activation at stage s on tick t is microbatch t - s; its
        # positions (rope) must travel with it.  Clamped: bubble ticks
        # compute garbage that is never banked.
        pos = lax.dynamic_index_in_dim(
            pos_mb, jnp.clip(t - stage, 0, m - 1), axis=0, keepdims=False)
        y = stage_fn(cur, pos)
        out_id = t - (n_stages - 1)
        bank = (stage == n_stages - 1) & (out_id >= 0)
        banked = lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(out_id, 0, m - 1), axis=0)
        out = jnp.where(bank, banked, out)
        nxt = lax.ppermute(
            y, pp, [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (nxt, out), None

    (_, out), _ = lax.scan(tick, (buf, out), jnp.arange(ticks))
    # banked outputs live on the last stage; psum replicates them so every
    # pp shard computes the (cheap) head on its own dp x sp shard
    xf = lax.psum(out, pp).reshape(b_l, s_l, d)
    xf = _rms_norm(xf, final_norm)
    return jnp.einsum("bsd,vd->bsv", xf, lm_head,
                      preferred_element_type=jnp.float32)


def pp_forward_with_aux(params, tokens, positions, cfg, mesh):
    """Pipeline-parallel forward_with_aux: fp32 logits [B, S, vocab], aux=0.

    Same contract as transformer.forward_with_aux; dispatched from there
    when cfg.pp_axis is set."""
    if cfg.head_axis is not None:
        if cfg.head_axis not in mesh.shape:
            raise ValueError(
                f"head_axis {cfg.head_axis!r} is not an axis of the mesh "
                f"{dict(mesh.shape)}; set head_axis=None (ModelConfig "
                "defaults it to 'tp') or add the axis to the mesh")
        tp_size = mesh.shape[cfg.head_axis]
        if cfg.n_heads % tp_size or cfg.n_kv_heads % tp_size:
            raise ValueError(
                f"n_heads {cfg.n_heads} / n_kv_heads {cfg.n_kv_heads} not "
                f"divisible by {cfg.head_axis!r} mesh size {tp_size}")
    if cfg.n_experts:
        raise ValueError("pipeline parallelism does not compose with MoE")
    if cfg.attn_strategy != "burst":
        raise ValueError("pp path supports attn_strategy='burst' only")
    n_stages = mesh.shape[cfg.pp_axis]
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by pp={n_stages}")
    m = cfg.pp_microbatches
    dp = mesh.shape[cfg.batch_axis] if cfg.batch_axis else 1
    b_local = tokens.shape[0] // dp
    if b_local % m:
        raise ValueError(
            f"per-dp-shard batch {b_local} not divisible by "
            f"pp_microbatches {m}")

    if len(cfg.seq_axes) == 1:
        inter_axis, intra_axis = None, cfg.seq_axes[0]
    else:
        inter_axis, intra_axis = cfg.seq_axes
    bcfg = BurstConfig(
        causal=cfg.causal,
        layout=cfg.layout,
        intra_axis=intra_axis,
        inter_axis=inter_axis,
        backend=_resolve_backend(cfg.attn_backend),
        block_q=cfg.block_q,
        block_kv=cfg.block_kv,
    )
    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    tok_spec = P(cfg.batch_axis, seq_spec)
    # full per-leaf specs, not a P(pp) prefix: with tp the qkv/gate/up/wo/
    # down leaves are column/row-sliced over head_axis too, and a prefix
    # spec would hand every tp shard the full weights (double-counted after
    # the body's psums)
    layer_specs = param_specs(cfg)["layers"]
    fn = jax.shard_map(
        partial(_pp_forward_shard, cfg=cfg, bcfg=bcfg, m=m),
        mesh=mesh,
        in_specs=(layer_specs, P(), P(), P(), tok_spec, tok_spec),
        out_specs=P(cfg.batch_axis, seq_spec, None),
        check_vma=False,
    )
    logits = fn(params["layers"], params["embed"], params["final_norm"],
                params["lm_head"], tokens, positions)
    return logits, jnp.float32(0.0)
