"""Pipeline-parallel forward for the flagship LM: pp x dp x sp composed.

The reference has no pipeline parallelism (SURVEY.md §2.4 — DP/TP/PP are
delegated to host frameworks); `parallel/pipeline.py` provides the generic
GPipe-over-`lax.scan` building block, and this module is its integration
with the transformer + burst sequence ring (round-1 verdict item 5).

Composition problem: the regular forward path (transformer.forward_with_aux)
is GSPMD-style — einsums under jit with sharding constraints — and
`burst_attn` internally opens its own `shard_map` over the sequence axis.
`shard_map` does not nest, so a pipeline wrapper around that path can't
work.  TPU-native answer: ONE `shard_map` over the FULL (pp, dp, sp) mesh
whose body is fully manual per-shard code —

  * GPipe tick loop: stage p holds layers [p*L/P, (p+1)*L/P); activations
    `lax.ppermute` one hop along `pp` per tick; stage 0 injects microbatch
    t, the last stage banks finished microbatches (same schedule as
    parallel/pipeline.py:pipeline_shard).
  * attention: `burst_attn_shard` — the shard-level custom_vjp ring — runs
    over `sp` inside each stage (double ring over ("inter","intra") seq
    axes works the same way).
  * dp needs no code: the batch dim is sharded by the outer shard_map and
    parameter cotangents are psum'd across replicated axes by shard_map's
    transpose.

The backward pipeline schedule is free: jax.grad of scan + ppermute IS the
reverse schedule (ppermute transposes to the reverse permutation).

Tensor parallelism composes too: the megatron collectives GSPMD would infer
for the regular path are hand-written in `_layer_fwd` (column-sliced
qkv/gate/up, row-sliced wo/down, one psum over `tp` after each of attention
and the MLP).  So does MoE: `moe_shard` is already a per-shard function, so
the pp body calls it directly with the expert dim sliced over `ep` by the
outer shard_map; per-stage aux losses accumulate over live ticks only
(bubble ticks compute garbage) and psum over pp.  Embeddings/lm_head stay
replicated in pp mode (vocab-dim sharding would need a masked-lookup + psum
in the manual body for marginal memory win).

Parameter layout: `layers` holds stacked leaves [n_layers, ...] (dim 0
sharded over `pp`), not the regular list-of-dicts — see
transformer.init_params / stack_layers.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.burst import BurstConfig, burst_attn_shard, _resolve_backend
# the pure math MUST be shared with the regular path: a numerics change
# there must not silently break pp=1 vs pp=N parity (_mlp's dense path is
# per-shard pure math too — cfg=None selects it)
from .transformer import _attn_out, _mlp, _qkv_proj, _rms_norm, param_specs
from ..utils.compat import axis_size, shard_map


def stack_layers(layers):
    """List-of-layer-dicts -> one pytree with a leading [n_layers, ...] axis
    (the layout the pp path shards over the `pp` mesh axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def unstack_layers(stacked, n_layers):
    """Inverse of stack_layers (e.g. to run a pp checkpoint without pp)."""
    return [jax.tree.map(lambda a: a[i], stacked) for i in range(n_layers)]


def _moe_block(p, x, cfg):
    """Per-shard routed MoE (training path): the same moe_shard call the
    regular path's _mlp makes inside ITS shard_map, minus the wrapper —
    here the outer pp shard_map has already sliced the expert dim over
    `ep`.  Routing groups are this stage's (microbatch x seq-shard) tokens.
    Returns (out, aux) with aux pmean'd over every token-sharding axis."""
    from ..parallel.moe import MoEParams, capacity_for, moe_shard

    h = _rms_norm(x, p["mlp_norm"])
    bb, ss, dd = h.shape
    tokens = bb * ss
    cap = capacity_for(tokens, cfg.n_experts, cfg.moe_top_k,
                       cfg.moe_capacity_factor)
    mp = MoEParams(p["router"], p["w_gate"], p["w_up"], p["w_down"])
    y, aux, _ = moe_shard(mp, h.reshape(tokens, dd), top_k=cfg.moe_top_k,
                          capacity=cap, axis=cfg.expert_axis)
    rest = tuple(a for a in (cfg.batch_axis, *cfg.seq_axes)
                 if a is not None and a != cfg.expert_axis)
    if rest:
        aux = lax.pmean(aux, rest)
    return y.reshape(bb, ss, dd), aux


def _layer_fwd(p, x, positions, cfg, bcfg: BurstConfig, seg=None):
    """One transformer block, per-shard (x [mb, s_local, d]) ->
    (x, aux_loss).

    Tensor parallelism is hand-written megatron: qkv/gate/up weights arrive
    column-sliced over `tp` (so the einsums run on the local head/ffn
    shard), wo/down row-sliced, and the two psums below reduce the partial
    outputs — exactly the collectives GSPMD infers for the regular path's
    param_specs, made explicit because this body is inside shard_map.
    MoE layers (cfg.n_experts) route per-stage token groups over `ep`;
    expert weights are replicated across tp (as in the regular path), so
    the MoE output needs no tp psum."""
    tp = cfg.head_axis
    q, k, v = _qkv_proj(p, x, positions, cfg)
    o = burst_attn_shard(q, k, v, bcfg, seg)
    attn = _attn_out(p, o)
    if tp is not None:
        attn = lax.psum(attn, tp)
    x = x + attn
    if cfg.n_experts:
        mlp_out, aux = _moe_block(p, x, cfg)
    else:
        mlp_out, aux = _mlp(p, x)[0], jnp.float32(0.0)
        if tp is not None:
            mlp_out = lax.psum(mlp_out, tp)
    return x + mlp_out, aux


def _pp_forward_shard(layers_p, embed, final_norm, lm_head, tokens, positions,
                      segments=None, *, cfg, bcfg: BurstConfig, m: int):
    """Per-shard body: embed -> GPipe ticks over `pp` -> head.

    layers_p: this stage's layers, leaves [L/P, ...]; tokens/positions
    [b_local, s_local] (dp x sp shard)."""
    pp = cfg.pp_axis
    n_stages = axis_size(pp)
    stage = lax.axis_index(pp)
    b_l, s_l = tokens.shape
    x = embed.astype(cfg.dtype)[tokens]
    d = x.shape[-1]
    mb = b_l // m
    x_mb = x.reshape(m, mb, s_l, d)
    pos_mb = positions.reshape(m, mb, s_l)
    seg_mb = (None if segments is None
              else segments.reshape(m, mb, s_l))

    def stage_fn(x, pos, seg):
        def body(carry, p):
            x, aux = carry
            x, aux_l = _layer_fwd(p, x, pos, cfg, bcfg, seg)
            return (x, aux + aux_l), None

        if cfg.remat:
            body = jax.checkpoint(body)
        (x, aux), _ = lax.scan(body, (x, jnp.float32(0.0)), layers_p)
        return x, aux

    ticks = m + n_stages - 1
    buf = jnp.zeros_like(x_mb[0])  # activation arriving from the left
    out = jnp.zeros_like(x_mb)     # banked results (last stage only)

    def tick(carry, t):
        buf, out, aux_acc = carry
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.minimum(t, m - 1), axis=0, keepdims=False)
        cur = jnp.where(stage == 0, inject, buf)
        # the activation at stage s on tick t is microbatch t - s; its
        # positions (rope) must travel with it.  Clamped: bubble ticks
        # compute garbage that is never banked.
        mb_id = t - stage
        pos = lax.dynamic_index_in_dim(
            pos_mb, jnp.clip(mb_id, 0, m - 1), axis=0, keepdims=False)
        seg = (None if seg_mb is None else lax.dynamic_index_in_dim(
            seg_mb, jnp.clip(mb_id, 0, m - 1), axis=0, keepdims=False))
        y, aux_t = stage_fn(cur, pos, seg)
        # MoE aux from bubble ticks (garbage activations) must not count
        live = (mb_id >= 0) & (mb_id < m)
        aux_acc = aux_acc + jnp.where(live, aux_t, 0.0)
        out_id = t - (n_stages - 1)
        bank = (stage == n_stages - 1) & (out_id >= 0)
        banked = lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(out_id, 0, m - 1), axis=0)
        out = jnp.where(bank, banked, out)
        nxt = lax.ppermute(
            y, pp, [(i, (i + 1) % n_stages) for i in range(n_stages)])
        return (nxt, out, aux_acc), None

    (_, out, aux_acc), _ = lax.scan(
        tick, (buf, out, jnp.float32(0.0)), jnp.arange(ticks))
    # banked outputs live on the last stage; psum replicates them so every
    # pp shard computes the (cheap) head on its own dp x sp shard.  aux:
    # each stage holds its own layers' aux summed over its m live ticks —
    # psum over pp completes the layer sum, / m averages microbatches
    # (identical to the regular path when m == 1).
    aux = lax.psum(aux_acc, pp) / m
    xf = lax.psum(out, pp).reshape(b_l, s_l, d)
    xf = _rms_norm(xf, final_norm)
    logits = jnp.einsum("bsd,vd->bsv", xf, lm_head,
                        preferred_element_type=jnp.float32)
    return logits, aux


def pp_forward_with_aux(params, tokens, positions, cfg, mesh,
                        segment_ids=None):
    """Pipeline-parallel forward_with_aux: fp32 logits [B, S, vocab] + the
    MoE aux loss (0 for dense models).

    Same contract as transformer.forward_with_aux; dispatched from there
    when cfg.pp_axis is set.  With pp_microbatches > 1 the MoE aux (and
    routing groups) are per-microbatch — the mean over microbatches, which
    differs from the regular path's full-batch routing exactly the way
    grad-accumulation microbatching does; m == 1 matches it exactly."""
    if cfg.head_axis is not None:
        if cfg.head_axis not in mesh.shape:
            raise ValueError(
                f"head_axis {cfg.head_axis!r} is not an axis of the mesh "
                f"{dict(mesh.shape)}; set head_axis=None (ModelConfig "
                "defaults it to 'tp') or add the axis to the mesh")
        tp_size = mesh.shape.get(cfg.head_axis, 1)
        if cfg.n_heads % tp_size or cfg.n_kv_heads % tp_size:
            raise ValueError(
                f"n_heads {cfg.n_heads} / n_kv_heads {cfg.n_kv_heads} not "
                f"divisible by {cfg.head_axis!r} mesh size {tp_size}")
        if not cfg.n_experts and cfg.d_ff % tp_size:
            raise ValueError(
                f"d_ff {cfg.d_ff} not divisible by {cfg.head_axis!r} mesh "
                f"size {tp_size} (the dense MLP weights are column-sliced "
                "over tp)")
    if cfg.n_experts and cfg.expert_axis is not None:
        if cfg.expert_axis not in mesh.shape:
            raise ValueError(
                f"expert_axis {cfg.expert_axis!r} is not an axis of the "
                f"mesh {dict(mesh.shape)}")
        ep_size = mesh.shape.get(cfg.expert_axis, 1)
        if cfg.n_experts % ep_size:
            raise ValueError(
                f"n_experts {cfg.n_experts} not divisible by "
                f"expert_axis {cfg.expert_axis!r} size {ep_size}")
    if cfg.attn_strategy != "burst":
        raise ValueError("pp path supports attn_strategy='burst' only")
    if cfg.pp_axis not in mesh.shape:
        raise ValueError(
            f"pp_axis {cfg.pp_axis!r} is not an axis of the mesh "
            f"{dict(mesh.shape)}")
    if cfg.batch_axis is not None and cfg.batch_axis not in mesh.shape:
        raise ValueError(
            f"batch_axis {cfg.batch_axis!r} is not an axis of the mesh "
            f"{dict(mesh.shape)}; set batch_axis=None or add a dp axis")
    n_stages = mesh.shape.get(cfg.pp_axis, 1)
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by pp={n_stages}")
    m = cfg.pp_microbatches
    dp = mesh.shape.get(cfg.batch_axis, 1) if cfg.batch_axis else 1
    b_local = tokens.shape[0] // dp
    if b_local % m:
        raise ValueError(
            f"per-dp-shard batch {b_local} not divisible by "
            f"pp_microbatches {m}")

    if len(cfg.seq_axes) == 1:
        inter_axis, intra_axis = None, cfg.seq_axes[0]
    else:
        inter_axis, intra_axis = cfg.seq_axes
    bcfg = BurstConfig(
        causal=cfg.causal,
        layout=cfg.layout,
        intra_axis=intra_axis,
        inter_axis=inter_axis,
        backend=_resolve_backend(cfg.attn_backend),
        block_q=cfg.block_q,
        block_kv=cfg.block_kv,
        window=cfg.window,
    )
    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    tok_spec = P(cfg.batch_axis, seq_spec)
    # full per-leaf specs, not a P(pp) prefix: with tp the qkv/gate/up/wo/
    # down leaves are column/row-sliced over head_axis too, and a prefix
    # spec would hand every tp shard the full weights (double-counted after
    # the body's psums)
    layer_specs = param_specs(cfg)["layers"]
    in_specs = [layer_specs, P(), P(), P(), tok_spec, tok_spec]
    args = [params["layers"], params["embed"], params["final_norm"],
            params["lm_head"], tokens, positions]
    if segment_ids is not None:
        in_specs.append(tok_spec)
        args.append(jnp.asarray(segment_ids, jnp.int32))
    fn = shard_map(
        partial(_pp_forward_shard, cfg=cfg, bcfg=bcfg, m=m),
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(cfg.batch_axis, seq_spec, None), P()),
        check_vma=False,
    )
    logits, aux = fn(*args)
    return logits, aux
