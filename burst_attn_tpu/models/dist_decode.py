"""Distributed long-context inference: ring prefill with a SEQUENCE-SHARDED
KV cache, then LSE-merged decode across the shards.

models/decode.py keeps the whole cache on one replica — fine up to the HBM
of a single chip, but this framework's point is sequences that need the
ring.  Here the prompt's KV cache never leaves its sequence shards:

  * prefill: the training forward (burst ring attention over `sp`, any
    layout) runs once over the prompt, capturing each layer's rope'd K/V.
    The cache stays sharded [B, Nkv, S/W, D] per device, in LAYOUT order —
    decode never needs the order: a new token attends ALL cached tokens, and
    attention is permutation-invariant when everything is visible.
  * decode: per layer, the new token's q computes a PARTIAL online-softmax
    against the local cache shard; the partials merge across the `sp` axis
    in log space (pmax of the row max, psum of the rescaled sum/accumulator
    — the same merge the ring uses, ops/tile.py), then merge once more with
    a small REPLICATED buffer holding the tokens generated so far.  New
    tokens append to that replicated buffer: O(steps) memory, no shard
    surgery, exact attention.

Single-axis sp mesh (pass the same mesh used for prefill). Generated-token
budget = the replicated buffer size = `steps`.
"""

from functools import partial
from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .transformer import ModelConfig, _attn_out, _mlp, _qkv_proj, _rms_norm
from ..parallel import layouts
from ..parallel.burst import burst_attn
from ..utils.compat import shard_map


class DistCache(NamedTuple):
    # per layer, sequence-sharded over sp (layout order), dtype = cfg.dtype
    k_shard: Tuple[jax.Array, ...]   # each [B, Nkv, S, D]
    v_shard: Tuple[jax.Array, ...]
    # per layer, replicated recent-token buffers
    k_new: Tuple[jax.Array, ...]     # each [B, Nkv, R, D]
    v_new: Tuple[jax.Array, ...]
    n_new: jax.Array                 # scalar int32: valid positions in *_new


def dist_prefill(params, tokens, cfg: ModelConfig, mesh, *, gen_budget: int):
    """Absorb a [B, S] prompt (natural order) with the sharded forward.

    Returns (last_logits [B, vocab] fp32, DistCache).  S must divide by the
    sp world; gen_budget sizes the replicated recent-KV buffers.
    """
    b, s = tokens.shape
    world = 1
    for a in cfg.seq_axes:
        world *= mesh.shape.get(a, 1)
    perm = layouts.seq_permutation(cfg.layout, s, world)
    pos = jnp.broadcast_to(jnp.asarray(perm, jnp.int32)[None, :], (b, s))
    tokens_l = jnp.take(tokens, jnp.asarray(perm), axis=1)

    seq_spec = cfg.seq_axes if len(cfg.seq_axes) > 1 else cfg.seq_axes[0]
    act_spec = NamedSharding(mesh, P(cfg.batch_axis, seq_spec, None))
    kv_spec = NamedSharding(mesh, P(cfg.batch_axis, None, seq_spec, None))

    x = params["embed"].astype(cfg.dtype)[tokens_l]
    x = lax.with_sharding_constraint(x, act_spec)
    ks, vs = [], []
    for p in params["layers"]:
        q, k, v = _qkv_proj(p, x, pos, cfg)
        k = lax.with_sharding_constraint(k.astype(cfg.dtype), kv_spec)
        v = lax.with_sharding_constraint(v.astype(cfg.dtype), kv_spec)
        ks.append(k)
        vs.append(v)
        o = burst_attn(
            q, k, v, mesh=mesh, seq_axes=cfg.seq_axes, causal=cfg.causal,
            layout=cfg.layout, backend=cfg.attn_backend,
            block_q=cfg.block_q, block_kv=cfg.block_kv,
            batch_axes=cfg.batch_axis, head_axes=cfg.head_axis,
            window=cfg.window,
        )
        x = x + _attn_out(p, o)
        # inference=True: drop-free MoE routing, matching decode.py's prefill
        m, _ = _mlp(p, x, cfg, mesh, inference=True)
        x = lax.with_sharding_constraint(x + m, act_spec)

    xf = _rms_norm(x, params["final_norm"])
    # only ONE position feeds decoding; the full [B, S, vocab] fp32 logits
    # would be GBs at the contexts this module exists for.  The LAST token
    # in natural order sits at layout position inv_perm[s-1] — a host-side
    # numpy scalar (perm is a layout table, never traced), so it indexes xf
    # as a static constant under jit with no int() coercion needed.
    last_pos = layouts.inverse_permutation(perm)[s - 1]
    last_logits = jnp.einsum("bd,vd->bv", xf[:, last_pos], params["lm_head"],
                             preferred_element_type=jnp.float32)

    shape_new = (b, cfg.n_kv_heads, gen_budget, cfg.d_head)
    zeros_new = tuple(jnp.zeros(shape_new, cfg.dtype)
                      for _ in range(cfg.n_layers))
    cache = DistCache(tuple(ks), tuple(vs), zeros_new,
                      tuple(jnp.zeros(shape_new, cfg.dtype)
                            for _ in range(cfg.n_layers)),
                      jnp.int32(0))
    return last_logits, cache


def _merge(parts):
    """Log-space merge of [(m, l, acc)] partials (m [B,N,1], l [B,N,1],
    acc [B,N,1,D] unnormalized)."""
    m_g = parts[0][0]
    for m, _, _ in parts[1:]:
        m_g = jnp.maximum(m_g, m)
    l_g = sum(l * jnp.exp(m - m_g) for m, l, _ in parts)
    acc_g = sum(acc * jnp.exp(m - m_g)[..., None] for m, _, acc in parts)
    return acc_g / jnp.maximum(l_g, 1e-30)[..., None]


def _partial_attn(q, k, v, scale, n_valid=None, col_lo=None):
    """Unnormalized online-softmax partial of q [B,N,1,D] against k/v
    [B,Nk,T,D]; positions >= n_valid masked, positions < col_lo masked
    (the sliding-window lower bound in this buffer's local coordinates).
    Returns (m, l, acc) with leading [B, N, 1] shape.  GQA via a grouped
    query axis — the dominant cache buffers are never repeated (decode.py's
    convention)."""
    b, n, _, d = q.shape
    nk, t = k.shape[1], k.shape[2]
    qg = q.reshape(b, nk, n // nk, 1, d)
    s = jnp.einsum("bngid,bnjd->bngij", qg, k,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(t, dtype=jnp.int32)[None, None, None, None, :]
    if n_valid is not None:
        s = jnp.where(cols < n_valid, s, -jnp.inf)
    if col_lo is not None:
        s = jnp.where(cols >= col_lo, s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    # fully-masked partial (empty recent buffer): exp(-inf - -inf) guard
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bngij,bnjd->bngid", p, v.astype(jnp.float32))
    m = jnp.where(jnp.isfinite(m), m, -1e30)  # neutral under max-merge
    return (m.reshape(b, n, 1), l.reshape(b, n, 1),
            acc.reshape(b, n, 1, d))


def dist_decode_step(params, token, position, cache: DistCache,
                     cfg: ModelConfig, mesh):
    """One token: [B] int32 -> (fp32 logits [B, vocab], updated cache)."""
    sp_axes = cfg.seq_axes
    scale = cfg.d_head**-0.5

    x = params["embed"].astype(cfg.dtype)[token][:, None, :]  # [B,1,d]
    pos = jnp.broadcast_to(position[None, None], (x.shape[0], 1)).astype(jnp.int32)

    k_new, v_new = [], []
    for li, p in enumerate(params["layers"]):
        q, k, v = _qkv_proj(p, x, pos, cfg)

        def shard_partial(q, kc, vc):
            col_lo = None
            if cfg.window is not None:
                # contig layout (enforced for windowed models): this shard's
                # first token is globally at part * s_local, so the band's
                # global lower bound position - window + 1 lands at local
                # column (position - window + 1) - part * s_local
                from ..parallel.ring import my_partition

                intra = sp_axes[-1]
                inter = sp_axes[0] if len(sp_axes) > 1 else None
                part = my_partition(intra, inter)
                col_lo = position - cfg.window + 1 - part * kc.shape[2]
            m, l, acc = _partial_attn(q, kc, vc, scale, col_lo=col_lo)
            # merge across the sequence shards in log space
            m_g = lax.pmax(m, sp_axes)
            w = jnp.exp(m - m_g)
            l_g = lax.psum(l * w, sp_axes)
            acc_g = lax.psum(acc * w[..., None], sp_axes)
            return m_g, l_g, acc_g

        seq_spec = sp_axes if len(sp_axes) > 1 else sp_axes[0]
        m_c, l_c, acc_c = shard_map(
            shard_partial, mesh=mesh,
            in_specs=(P(cfg.batch_axis, None, None, None),
                      P(cfg.batch_axis, None, seq_spec, None),
                      P(cfg.batch_axis, None, seq_spec, None)),
            out_specs=(P(cfg.batch_axis, None, None),
                       P(cfg.batch_axis, None, None),
                       P(cfg.batch_axis, None, None, None)),
            check_vma=False,
        )(q, cache.k_shard[li], cache.v_shard[li])

        # recent generated tokens (replicated) + the token being computed
        kr = lax.dynamic_update_slice(
            cache.k_new[li], k.astype(cfg.dtype), (0, 0, cache.n_new, 0))
        vr = lax.dynamic_update_slice(
            cache.v_new[li], v.astype(cfg.dtype), (0, 0, cache.n_new, 0))
        k_new.append(kr)
        v_new.append(vr)
        # recent buffer slot j holds global position (position - n_new) + j,
        # so the band's lower bound lands at slot n_new - window + 1
        rec_lo = (cache.n_new - cfg.window + 1
                  if cfg.window is not None else None)
        m_r, l_r, acc_r = _partial_attn(q, kr, vr, scale,
                                        n_valid=cache.n_new + 1,
                                        col_lo=rec_lo)
        o = _merge([(m_c, l_c, acc_c), (m_r, l_r, acc_r)]).astype(cfg.dtype)
        x = x + _attn_out(p, o)
        m_out, _ = _mlp(p, x, cfg, inference=True)
        x = x + m_out

    xf = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,vd->bsv", xf, params["lm_head"],
                        preferred_element_type=jnp.float32)[:, 0]
    cache = DistCache(cache.k_shard, cache.v_shard, tuple(k_new),
                      tuple(v_new), cache.n_new + 1)
    return logits, cache


def _page_partition(sp_axes):
    """Linear shard index over the (possibly nested) sequence axes — the
    same coordinate my_partition gives the ring."""
    from ..parallel.ring import my_partition

    intra = sp_axes[-1]
    inter = sp_axes[0] if len(sp_axes) > 1 else None
    return my_partition(intra, inter)


@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(2,))
def dist_paged_decode_step(params, tokens, state, cfg: ModelConfig, mesh):
    """One decode step against a PAGE-SHARDED pool: the pools split over
    the sequence axes along the page dimension (shard w owns global pages
    [w·P/W, (w+1)·P/W)), each shard computes an online-softmax partial
    over the table entries it owns, and the partials LSE-merge across the
    axes — dist_decode_step's merge, reading serving pages instead of a
    dense cache shard.

    This is the decode half of the million-token handoff
    (serving/handoff.py): ring prefill lands its K/V in pool pages in
    LAYOUT order with no re-layout copy, which is correct here because a
    decode token attends EVERY cached position (validity is "is this
    table entry a real token", not an ordering) and full-visibility
    attention is permutation-invariant.  cfg.window must be None for
    exactly that reason.  The append itself is a global scatter (GSPMD
    splits it along the pools' sharding); table/lengths ride replicated.

    tokens [slots] int32 -> (fp32 logits [slots, vocab], new state).
    n_pages must divide by the sequence-axis world size.
    """
    from .paged_decode import PagedState
    from ..ops.paged_attention import quantize_tokens as _quant

    if cfg.window is not None:
        raise ValueError(
            "dist_paged_decode_step requires cfg.window=None: pages hold "
            "layout-order tokens, and a windowed band over page order "
            "would not be the band over natural positions")
    sp_axes = cfg.seq_axes
    world = 1
    for a in sp_axes:
        world *= mesh.shape.get(a, 1)
    slots = tokens.shape[0]
    page = state.k_pages[0].shape[2]
    n_pages = state.k_pages[0].shape[0]
    if n_pages % world:
        raise ValueError(f"n_pages {n_pages} must divide by the sequence "
                         f"world {world} to shard the pool page dim")
    scale = cfg.d_head**-0.5
    group = cfg.n_heads // cfg.n_kv_heads
    live = state.lengths > 0
    pos = jnp.where(live, state.lengths, 0)
    x = params["embed"].astype(cfg.dtype)[tokens[:, None]]
    slot_page = state.lengths // page
    offset = state.lengths % page
    page_id = jnp.take_along_axis(state.page_table, slot_page[:, None],
                                  axis=1)[:, 0]
    boundary_unassigned = live & (page_id == 0)
    page_id = jnp.where(live, page_id, 0)
    lengths_new = state.lengths + live.astype(jnp.int32)
    quant = state.k_scales is not None
    seq_spec = sp_axes if len(sp_axes) > 1 else sp_axes[0]
    pool_spec = P(seq_spec, None, None, None)
    scale_spec = P(seq_spec, None, None)

    def shard_partial(qg, kp_l, vp_l, ks_l, vs_l, table, lens):
        part = _page_partition(sp_axes)
        p_loc = kp_l.shape[0]
        lo = part * p_loc
        owned = (table >= lo) & (table < lo + p_loc) & (table != 0)
        lp = jnp.clip(table - lo, 0, p_loc - 1)
        k_loc = kp_l[lp]                     # [slots, cols, Nkv, page, D]
        v_loc = vp_l[lp]
        if quant:
            k_loc = k_loc.astype(jnp.float32) * ks_l[lp][..., None]
            v_loc = v_loc.astype(jnp.float32) * vs_l[lp][..., None]
        cols = table.shape[1]
        k_loc = jnp.moveaxis(k_loc, 2, 1).reshape(
            slots, cfg.n_kv_heads, cols * page, cfg.d_head)
        v_loc = jnp.moveaxis(v_loc, 2, 1).reshape(
            slots, cfg.n_kv_heads, cols * page, cfg.d_head)
        col_pos = jnp.arange(cols * page, dtype=jnp.int32)[None, :]
        valid = (col_pos < lens[:, None]) \
            & jnp.repeat(owned, page, axis=1)
        s = jnp.einsum("bngd,bnjd->bngj", qg.astype(jnp.float32),
                       k_loc.astype(jnp.float32)) * scale
        s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
        m = jnp.max(s, axis=-1)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l = jnp.sum(p, axis=-1)
        acc = jnp.einsum("bngj,bnjd->bngd", p, v_loc.astype(jnp.float32))
        m = jnp.where(jnp.isfinite(m), m, -1e30)  # neutral under pmax
        m_g = lax.pmax(m, sp_axes)
        w = jnp.exp(m - m_g)
        l_g = lax.psum(l * w, sp_axes)
        acc_g = lax.psum(acc * w[..., None], sp_axes)
        return acc_g / jnp.maximum(l_g, 1e-30)[..., None]

    k_pools, v_pools, k_scs, v_scs = [], [], [], []
    for li, (p, kp, vp) in enumerate(zip(params["layers"], state.k_pages,
                                         state.v_pages)):
        q, k, v = _qkv_proj(p, x, pos[:, None], cfg)
        k_row, v_row = k[:, :, 0], v[:, :, 0]
        ks = vs = None
        if quant:
            k8, k_s = _quant(k_row)
            v8, v_s = _quant(v_row)
            kp = kp.at[page_id, :, offset].set(k8)
            vp = vp.at[page_id, :, offset].set(v8)
            ks = state.k_scales[li].at[page_id, :, offset].set(k_s)
            vs = state.v_scales[li].at[page_id, :, offset].set(v_s)
        else:
            kp = kp.at[page_id, :, offset].set(k_row.astype(kp.dtype))
            vp = vp.at[page_id, :, offset].set(v_row.astype(vp.dtype))
        qg = q.reshape(slots, cfg.n_kv_heads, group, cfg.d_head)
        in_specs = [P(None, None, None, None), pool_spec, pool_spec,
                    scale_spec if quant else P(),
                    scale_spec if quant else P(),
                    P(None, None), P(None)]
        o = shard_map(
            shard_partial, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=P(None, None, None, None), check_vma=False,
        )(qg, kp, vp,
          ks if quant else jnp.zeros((), cfg.dtype),
          vs if quant else jnp.zeros((), cfg.dtype),
          state.page_table, lengths_new)
        o = o.reshape(slots, cfg.n_heads, 1, cfg.d_head).astype(cfg.dtype)
        x = x + _attn_out(p, o)
        m_out, _ = _mlp(p, x, cfg, inference=True)
        x = x + m_out
        k_pools.append(kp)
        v_pools.append(vp)
        k_scs.append(ks)
        v_scs.append(vs)
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,vd->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)[:, 0]
    logits = jnp.where(boundary_unassigned[:, None], jnp.nan, logits)
    return logits, PagedState(
        tuple(k_pools), tuple(v_pools), state.page_table, lengths_new,
        tuple(k_scs) if quant else None, tuple(v_scs) if quant else None)


def dist_generate(params, prompt, cfg: ModelConfig, mesh, *, steps: int,
                  temperature: float = 0.0, top_k=None, top_p=None, rng=None):
    """Greedy/sampled generation with the sequence-sharded prompt cache.

    prompt [B, S] natural order; returns [B, steps] tokens.  The decode loop
    is a python loop over jitted steps (the cache pytree's shardings are
    stable, so each step reuses one compiled program).  Sampling semantics
    (temperature / top-k / top-p) are decode.sample_logits's.
    """
    from .decode import sample_logits

    b, s = prompt.shape
    last_logits, cache = jax.jit(
        partial(dist_prefill, cfg=cfg, mesh=mesh, gen_budget=steps)
    )(params, prompt)
    rng = jax.random.PRNGKey(0) if rng is None else rng

    # jitted with the sampling config closed over (Python constants): the
    # per-token path must stay one cached program per step, not ~8 eager
    # full-vocab dispatches through the device tunnel
    @jax.jit
    def pick(logits, key):
        return sample_logits(logits, key, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    step_fn = jax.jit(partial(dist_decode_step, cfg=cfg, mesh=mesh))
    keys = jax.random.split(rng, steps + 1)
    token = pick(last_logits, keys[0])
    out = [token]
    for i in range(steps - 1):
        logits, cache = step_fn(params, token, jnp.int32(s + i), cache)
        token = pick(logits, keys[i + 1])
        out.append(token)
    return jnp.stack(out, axis=1)
