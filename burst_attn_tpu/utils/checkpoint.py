"""Checkpoint / resume for the training layer (orbax-backed).

The reference has NO checkpointing (SURVEY.md §5 — it is an op library and
delegates training-state concerns to host frameworks).  The TPU framework is
a full training stack, so checkpointing is first-class here: sharded arrays
are saved/restored in their native on-device layout (orbax handles per-shard
IO and multi-host coordination), and restore rebuilds the exact
NamedSharding placement from the model's PartitionSpec tree, so a run can
resume on the same mesh without any gather/scatter through host memory.

Usage:
    ckpt = Checkpointer(dir)
    ckpt.save(step, state)                      # state = (params, opt_state)
    state, step = ckpt.restore_latest(cfg, tcfg, mesh)   # sharded restore
"""

import os
from typing import Any, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Checkpointer:
    """Thin orbax CheckpointManager wrapper bound to one run directory."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state, *, wait: bool = False) -> None:
        """Save (params, opt_state) at `step`; async by default."""
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, cfg, tcfg, mesh: Mesh) -> Tuple[Any, int]:
        """Restore the state saved at `step`, placed per the model's
        PartitionSpec tree on `mesh` (no host round trip of full arrays)."""
        from ..models.train import _optimizer, _state_specs, init_params

        def shapes():
            params = init_params(jax.random.PRNGKey(0), cfg)
            return params, _optimizer(tcfg).init(params)

        params_shape, opt_shape = jax.eval_shape(shapes)
        pspecs, opt_specs = _state_specs(cfg, tcfg, params_shape)

        def as_target(shape_leaf, spec):
            return jax.ShapeDtypeStruct(
                shape_leaf.shape, shape_leaf.dtype,
                sharding=NamedSharding(mesh, spec),
            )

        target = (
            jax.tree.map(as_target, params_shape, pspecs,
                         is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct)),
            jax.tree_util.tree_map(
                as_target, opt_shape, opt_specs,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct)),
        )
        state = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(target)
        )
        return state, step

    def restore_latest(self, cfg, tcfg, mesh: Mesh) -> Tuple[Any, Optional[int]]:
        """Restore the most recent checkpoint, or (None, None) if none."""
        step = self.latest_step()
        if step is None:
            return None, None
        return self.restore(step, cfg, tcfg, mesh)

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
