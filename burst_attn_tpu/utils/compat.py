"""JAX version compatibility shims.

The framework targets the current jax API (jax.shard_map with check_vma,
pltpu.CompilerParams) but must also run on the 0.4.x line the container
pins, where those spellings live in jax.experimental and carry their old
names (shard_map's check_rep, pltpu.TPUCompilerParams).  Every call site
goes through this module so the version probe happens exactly once.
"""

import jax

_shard_map_new = getattr(jax, "shard_map", None)
if _shard_map_new is None:
    from jax.experimental.shard_map import shard_map as _shard_map_old
else:
    _shard_map_old = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map across versions; `check_vma` maps to the old check_rep."""
    if _shard_map_new is not None:
        return _shard_map_new(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_vma=check_vma)
    return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name):
    """lax.axis_size across versions.

    Older jax has no lax.axis_size; lax.psum(1, name) is the classic idiom
    and constant-folds to a Python int under shard_map's static mesh."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def tpu_compiler_params(**kwargs):
    """pltpu.CompilerParams (new) / pltpu.TPUCompilerParams (0.4.x)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def profile_options(host_tracer_level: int):
    """jax.profiler.ProfileOptions, or None where the API predates it."""
    cls = getattr(jax.profiler, "ProfileOptions", None)
    if cls is None:
        return None
    opts = cls()
    opts.host_tracer_level = host_tracer_level
    return opts
