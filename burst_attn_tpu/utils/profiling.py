"""Tracing / profiling utilities.

The reference has no profiling subsystem beyond its benchmark harness
(SURVEY.md §5); the only debug aid is each rank's `record` list of visited
partition ids (burst_attn_interface.py:213-217).  Here both live in the
framework: XLA profiler capture (viewable in XProf/TensorBoard, incl. the
collective-permute/compute overlap of the ring scan) and the ring-schedule
replay check.

    with trace("/tmp/profile"):
        step(state, batch)          # -> /tmp/profile/plugins/profile/...

    timer = StepTimer()
    for batch in data:
        with timer:
            state, _ = step(state, batch)
    print(timer.summary())
"""

import contextlib
import time
from typing import List, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture an XLA profiler trace of the enclosed block.

    On TPU this records device timelines (kernel + collective activity) —
    the tool for confirming the ring's permute/compute overlap that the
    reference eyeballed with CUDA stream timing.
    """
    from .compat import profile_options

    opts = profile_options(host_tracer_level)
    if opts is not None:
        jax.profiler.start_trace(log_dir, profiler_options=opts)
    else:  # older jax: no ProfileOptions — default tracer levels
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span that shows up on the profiler timeline (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock step timer that blocks on the step's OUTPUTS at exit so
    device work is included without serializing unrelated async work (a
    global live-array sweep would block on e.g. the next batch's
    host-to-device prefetch and destroy the IO/compute overlap):

        with timer as t:
            state, metrics = step(state, batch)
            t.watch(state)
    """

    def __init__(self):
        self.times: List[float] = []
        self._t0: Optional[float] = None
        self._watched = None

    def watch(self, *outputs):
        """Register the step's outputs; exit blocks until they are ready."""
        self._watched = outputs
        return outputs[0] if len(outputs) == 1 else outputs

    def __enter__(self):
        self._watched = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            if self._watched is None:
                raise RuntimeError("StepTimer: call t.watch(outputs) inside the block")
            jax.block_until_ready(self._watched)
            self.times.append(time.perf_counter() - self._t0)
        self._watched = None
        return False

    def summary(self, skip_first: int = 1) -> dict:
        """Stats over recorded steps (first `skip_first` dropped: compile)."""
        ts = self.times[skip_first:] or self.times
        if not ts:
            return {"steps": 0, "mean_s": 0.0, "min_s": 0.0, "max_s": 0.0}
        return {
            "steps": len(ts),
            "mean_s": sum(ts) / len(ts),
            "min_s": min(ts),
            "max_s": max(ts),
        }
