"""Tracing / profiling utilities.

The reference has no profiling subsystem beyond its benchmark harness
(SURVEY.md §5); the only debug aid is each rank's `record` list of visited
partition ids (burst_attn_interface.py:213-217).  Here both live in the
framework: XLA profiler capture (viewable in XProf/TensorBoard, incl. the
collective-permute/compute overlap of the ring scan) and the ring-schedule
replay check.

    with trace("/tmp/profile"):
        step(state, batch)          # -> /tmp/profile/plugins/profile/...

    timer = StepTimer()
    for batch in data:
        with timer:
            state, _ = step(state, batch)
    print(timer.summary())
"""

import contextlib
import time
from typing import List, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture an XLA profiler trace of the enclosed block.

    On TPU this records device timelines (kernel + collective activity) —
    the tool for confirming the ring's permute/compute overlap that the
    reference eyeballed with CUDA stream timing.
    """
    opts = jax.profiler.ProfileOptions()
    opts.host_tracer_level = host_tracer_level
    jax.profiler.start_trace(log_dir, profiler_options=opts)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span that shows up on the profiler timeline (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock step timer with a blocking fetch at each exit so device
    work is included (use around jitted steps)."""

    def __init__(self):
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        for a in jax.live_arrays():
            if not a.is_deleted():  # donated buffers linger in live_arrays
                a.block_until_ready()
        self.times.append(time.perf_counter() - self._t0)
        return False

    def summary(self, skip_first: int = 1) -> dict:
        """Stats over recorded steps (first `skip_first` dropped: compile)."""
        ts = self.times[skip_first:] or self.times
        return {
            "steps": len(ts),
            "mean_s": sum(ts) / len(ts),
            "min_s": min(ts),
            "max_s": max(ts),
        }
