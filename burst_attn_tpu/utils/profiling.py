"""XLA profiler capture + deprecation shims for the moved timing helpers.

The reference has no profiling subsystem beyond its benchmark harness
(SURVEY.md §5); here the device side lives in this module and the host
side in the obs subsystem:

  * `trace(log_dir)` — XLA profiler capture (XProf/TensorBoard, incl. the
    collective-permute/compute overlap of the ring scan).  Device
    timelines are profiler state, not obs registry state, so it stays
    here.
  * `StepTimer` / `annotate` — MOVED to `burst_attn_tpu.obs.spans` (they
    are host-side timing, which is obs's job; StepTimer now also feeds the
    registry histogram `span.step_timer`).  Re-exported here so existing
    imports keep working; new code should import from `burst_attn_tpu.obs`.

    with trace("/tmp/profile"):
        step(state, batch)          # -> /tmp/profile/plugins/profile/...
"""

import contextlib

import jax

# deprecation shims — canonical home is obs.spans (see module docstring)
from ..obs.spans import StepTimer, annotate  # noqa: F401

__all__ = ["trace", "StepTimer", "annotate"]


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture an XLA profiler trace of the enclosed block.

    On TPU this records device timelines (kernel + collective activity) —
    the tool for confirming the ring's permute/compute overlap that the
    reference eyeballed with CUDA stream timing.  obs spans entered inside
    the block appear on the same timeline (spans wrap
    jax.profiler.TraceAnnotation).
    """
    from .compat import profile_options

    opts = profile_options(host_tracer_level)
    if opts is not None:
        jax.profiler.start_trace(log_dir, profiler_options=opts)
    else:  # older jax: no ProfileOptions — default tracer levels
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
