"""Logging utilities — parity with the reference's log_helper
(burst_attn/log_helper.py:2-16) plus rank-aware helpers replacing its
print_rank / log_rank0 (reference comm.py:324-333, :31)."""

import logging
import sys
from typing import Optional

import jax

_FMT = "%(asctime)s %(name)s %(levelname)s: %(message)s"


def get_logger(name: str, level=logging.INFO, file: Optional[str] = None):
    """Per-name logger with stream (and optional file) handlers, configured
    once."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        logger.setLevel(level)
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(sh)
        if file:
            fh = logging.FileHandler(file)
            fh.setFormatter(logging.Formatter(_FMT))
            logger.addHandler(fh)
        logger.propagate = False
    return logger


def is_primary() -> bool:
    """True on the host that should emit logs (process 0)."""
    return jax.process_index() == 0


def print_rank0(*args, **kwargs):
    if is_primary():
        print(*args, **kwargs)


def log_rank0(logger, msg, level=logging.INFO):
    if is_primary():
        logger.log(level, msg)
