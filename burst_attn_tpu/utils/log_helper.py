"""Logging utilities — parity with the reference's log_helper
(burst_attn/log_helper.py:2-16) plus rank-aware helpers replacing its
print_rank / log_rank0 (reference comm.py:324-333, :31).

The handler setup itself moved to the obs subsystem (obs/logs.py) so every
logger in the process is counted in the metrics registry
(`log.events{level=...}`); `get_logger` here is a thin delegating shim —
same signature, same handlers/format as before."""

import logging
from typing import Optional

import jax


def get_logger(name: str, level=logging.INFO, file: Optional[str] = None):
    """Per-name logger with stream (and optional file) handlers, configured
    once.  Delegates to burst_attn_tpu.obs.logs.get_logger (records are
    counted in the obs registry); import lazily so utils stays importable
    while the obs package itself initializes."""
    from ..obs.logs import get_logger as _obs_get_logger

    return _obs_get_logger(name, level=level, file=file)


def is_primary() -> bool:
    """True on the host that should emit logs (process 0)."""
    return jax.process_index() == 0


def print_rank0(*args, **kwargs):
    if is_primary():
        print(*args, **kwargs)


def log_rank0(logger, msg, level=logging.INFO):
    if is_primary():
        logger.log(level, msg)
