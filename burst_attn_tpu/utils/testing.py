"""Test helpers — the reference's numeric checker (test/checker.py) rebuilt.

check_close keeps the reference's tolerance convention (rtol=1e-3, atol=1e-2
in half precision, test/checker.py:10) and its NaN probe (checker.py:21).
"""

import jax.numpy as jnp
import numpy as np

RTOL = 1e-3
ATOL = 1e-2


def check_close(a, b, rtol=RTOL, atol=ATOL, msg=""):
    a = np.asarray(a, dtype=np.float32)
    b = np.asarray(b, dtype=np.float32)
    assert not np.isnan(a).any(), f"NaN in actual {msg}"
    assert not np.isnan(b).any(), f"NaN in expected {msg}"
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol, err_msg=msg)


def random_qkv(key, batch, heads, seq, dim, kv_heads=None, dtype=jnp.bfloat16):
    import jax

    kv_heads = kv_heads or heads
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (batch, heads, seq, dim), dtype=dtype)
    k = jax.random.normal(kk, (batch, kv_heads, seq, dim), dtype=dtype)
    v = jax.random.normal(kv, (batch, kv_heads, seq, dim), dtype=dtype)
    do = jax.random.normal(kg, (batch, heads, seq, dim), dtype=dtype)
    return q, k, v, do
