"""Multi-host (multi-process) runtime setup.

The reference reaches multi-node through torchrun + NCCL rendezvous
(reference test/test.sh:6, comm.py:74-101 env-var rank plumbing).  The JAX
equivalent is the multi-controller runtime: every host runs the same
program, `jax.distributed.initialize` performs the rendezvous, and
`jax.devices()` then spans all hosts, so a `Mesh` built from it carries DCN
(inter-host) axes transparently — the double ring's "inter" axis simply maps
onto the DCN dimension of the mesh.

Typical launch (per host):

    from burst_attn_tpu.utils import multihost
    multihost.initialize()                       # env-driven (TPU pods: automatic)
    mesh = multihost.make_hybrid_mesh(ici={"intra": 4}, dcn={"inter": 2})
    # burst_attn(..., seq_axes=("inter", "intra"), mesh=mesh)
"""

import os
from typing import Dict, Optional

import numpy as np
import jax


def _cluster_env() -> bool:
    """True iff the environment advertises a MULTI-host run (the signals
    jax.distributed's auto-detectors key on).  Single-valued forms —
    TPU_WORKER_HOSTNAMES=localhost (which single-chip TPU plugins set),
    one-task SLURM/MPI jobs — do not count."""
    for v in ("MEGASCALE_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "JAX_COORDINATOR_ADDRESS", "JOBSET_NAME"):
        if os.environ.get(v):
            return True
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return True
    for v in ("OMPI_COMM_WORLD_SIZE", "SLURM_NTASKS", "SLURM_NPROCS"):
        try:
            if int(os.environ.get(v, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Start the multi-controller runtime.  On TPU pods all arguments come
    from the environment; on CPU/GPU clusters pass them explicitly
    (reference analogue: torchrun's c10d rendezvous, test.sh:6).

    Must run before any JAX computation (backend init).  Intentionally does
    NOT probe jax.process_count() first — that would itself initialize the
    local backend and break the rendezvous.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # tolerate double-initialize, and — for the env-driven form only, on
        # a machine with no cluster environment — a backend that is already
        # up (single-process run that did JAX work before calling us).  In a
        # real cluster env the same error means the rendezvous was missed and
        # N duplicate single-host jobs would run: surface it.
        msg = str(e).lower()
        benign = "already" in msg or (
            not kwargs and "must be called before" in msg and not _cluster_env()
        )
        if not benign:
            raise
    except ValueError:
        # explicit arguments were wrong, or auto-detection failed on a host
        # that IS in a cluster (e.g. COORDINATOR_ADDRESS set but process ids
        # underivable) — both must surface, not degrade to N duplicate
        # single-host jobs.  Only a genuine no-cluster environment is benign.
        if kwargs or _cluster_env():
            raise


def make_hybrid_mesh(ici: Dict[str, int], dcn: Dict[str, int]):
    """Mesh whose `dcn` axes span hosts (outermost) and `ici` axes stay
    chip-local — the layout the double ring assumes (inter hop = DCN, intra
    ring = ICI; SURVEY.md §2.3 NCCL row).

    On real multi-host topologies this delegates to
    `mesh_utils.create_hybrid_device_mesh`, which orders ICI devices by
    physical torus coordinates (a naive id sort can make ICI-non-adjacent
    chips ring neighbors on 2D/3D slices, crippling collective-permute
    bandwidth).  Single-process simulated device sets (CPU
    host-platform-device-count) have no granules for it to split, so there
    we fall back to a process-major reshape — topology is moot.
    """
    from jax.sharding import Mesh

    names = tuple(dcn) + tuple(ici)
    shape = tuple(dcn.values()) + tuple(ici.values())
    n = int(np.prod(shape))
    devs = jax.devices()
    if n > len(devs):
        raise ValueError(f"mesh {dict(**dcn, **ici)} needs {n} devices, "
                         f"have {len(devs)}")
    if jax.process_count() > 1:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici.values()),
            dcn_mesh_shape=tuple(dcn.values()),
            devices=devs[:n],
        )
        # create_hybrid_device_mesh returns [*dcn, *ici]-shaped devices
        return Mesh(arr, names)
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    return Mesh(np.array(devs[:n]).reshape(shape), names)
