"""Multi-host (multi-process) runtime setup.

The reference reaches multi-node through torchrun + NCCL rendezvous
(reference test/test.sh:6, comm.py:74-101 env-var rank plumbing).  The JAX
equivalent is the multi-controller runtime: every host runs the same
program, `jax.distributed.initialize` performs the rendezvous, and
`jax.devices()` then spans all hosts, so a `Mesh` built from it carries DCN
(inter-host) axes transparently — the double ring's "inter" axis simply maps
onto the DCN dimension of the mesh.

Typical launch (per host):

    from burst_attn_tpu.utils import multihost
    multihost.initialize()                       # env-driven (TPU pods: automatic)
    mesh = multihost.make_hybrid_mesh(ici={"intra": 4}, dcn={"inter": 2})
    # burst_attn(..., seq_axes=("inter", "intra"), mesh=mesh)
"""

from typing import Dict, Optional

import numpy as np
import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Start the multi-controller runtime.  On TPU pods all arguments come
    from the environment; on CPU/GPU clusters pass them explicitly
    (reference analogue: torchrun's c10d rendezvous, test.sh:6).

    Must run before any JAX computation (backend init).  Intentionally does
    NOT probe jax.process_count() first — that would itself initialize the
    local backend and break the rendezvous.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # tolerate double-initialize; surface every other failure (a wrong
        # coordinator address silently falling back to single-host would be
        # far worse than an exception)
        if "already" not in str(e).lower():
            raise
    except ValueError:
        if kwargs:
            raise  # explicit arguments were wrong — do not swallow
        # auto-detection found no cluster environment: single-process run


def make_hybrid_mesh(ici: Dict[str, int], dcn: Dict[str, int]):
    """Mesh whose `dcn` axes span hosts (outermost) and `ici` axes stay
    chip-local — the layout the double ring assumes (inter hop = DCN, intra
    ring = ICI; SURVEY.md §2.3 NCCL row).

    Devices are ordered process-major, so reshaping to
    (*dcn_sizes, *ici_sizes) puts whole processes (hosts/slices) along the
    leading DCN axes; XLA then routes collectives on those axes over DCN and
    the trailing axes over ICI.
    """
    from jax.sharding import Mesh

    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    names = tuple(dcn) + tuple(ici)
    shape = tuple(dcn.values()) + tuple(ici.values())
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh {dict(**dcn, **ici)} needs {n} devices, "
                         f"have {len(devs)}")
    return Mesh(np.array(devs[:n]).reshape(shape), names)
