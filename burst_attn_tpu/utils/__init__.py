from . import testing

# checkpoint is imported lazily by callers (pulls in orbax); see
# utils/checkpoint.Checkpointer
__all__ = ["testing"]
