from . import testing, profiling

# checkpoint / multihost are imported lazily by callers (orbax / distributed
# runtime deps); see utils/checkpoint.Checkpointer, utils/multihost
__all__ = ["testing", "profiling"]
