from . import testing

__all__ = ["testing"]
