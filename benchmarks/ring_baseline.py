"""Score-materializing ring attention — the memory-inefficient baseline that
burst attention beats (fixed TPU port of the reference's ColossalAI-style
RingQK/RingAV, benchmarks/ring_attn.py:16-130; the reference copy is broken
at this snapshot — comm._ring passes 3 args to the 2-param ring_send_recv,
SURVEY.md §2.2).

Each device materializes its full [B*N, S/W, S] score block by rotating K
around the ring (RingQK), softmaxes it, then rotates V to form the output
(RingAV).  O(S^2/W) memory per device vs burst's O(S/W) — kept as the
benchmark baseline only.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from burst_attn_tpu.parallel.ring import ppermute_next
from burst_attn_tpu.utils.compat import axis_size, shard_map


def _ring_scores(q, k, axis_name):
    """s[global] = q_local @ k_global^T via W ppermute rounds.
    q, k: [B, N, S_local, D] -> scores [B, N, S_local, S_global]."""
    w = axis_size(axis_name)
    my = lax.axis_index(axis_name)

    def body(carry, r):
        k_cur, _ = carry
        k_next = ppermute_next(k_cur, axis_name)
        blk = jnp.einsum("bnid,bnjd->bnij", q, k_cur, preferred_element_type=jnp.float32)
        src = (my - r) % w  # whose K block we hold at round r
        return (k_next, None), (src, blk)

    (_, _), (srcs, blks) = lax.scan(body, (k, None), jnp.arange(w))
    # blks: [W, B, N, S_l, S_l]; scatter block r at global columns src*s_l
    s_l = q.shape[2]
    out = jnp.zeros(q.shape[:2] + (s_l, s_l * w), jnp.float32)

    def place(r, o):
        return lax.dynamic_update_slice_in_dim(o, blks[r], srcs[r] * s_l, axis=3)

    return lax.fori_loop(0, w, place, out)


def _ring_av(p, v, axis_name):
    """o = p @ v_global via W ppermute rounds.  p [B,N,S_l,S_g], v [B,N,S_l,D]."""
    w = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_l = v.shape[2]

    def body(carry, r):
        v_cur, acc = carry
        v_next = ppermute_next(v_cur, axis_name)
        src = (my - r) % w
        p_blk = lax.dynamic_slice_in_dim(p, src * s_l, s_l, axis=3)
        acc = acc + jnp.einsum(
            "bnij,bnjd->bnid", p_blk, v_cur, preferred_element_type=jnp.float32
        )
        return (v_next, acc), None

    acc0 = jnp.zeros(v.shape, jnp.float32)
    (_, acc), _ = lax.scan(body, (v, acc0), jnp.arange(w))
    return acc


def ring_attention_shard(q, k, v, axis_name: str, scale=None, causal=False):
    """Baseline ring attention on per-shard [B,N,S_l,D] arrays (contig layout).
    Materializes the [S_l, S_global] score matrix."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    w = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    s_l = q.shape[2]
    s = _ring_scores(q, k, axis_name) * scale
    if causal:
        rows = my * s_l + jnp.arange(s_l, dtype=jnp.int32)[:, None]
        cols = jnp.arange(s_l * w, dtype=jnp.int32)[None, :]
        s = jnp.where(cols <= rows, s, float("-inf"))
    p = jax.nn.softmax(s, axis=-1)
    return _ring_av(p, v, axis_name).astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis_name="sp", scale=None, causal=False):
    """Global-array entry point: q,k,v [B,N,S,D] sharded over axis_name on S."""
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(ring_attention_shard, axis_name=axis_name, scale=scale, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
