"""Paged-serving throughput on real TPU: prefill latency + steady-state
decode tokens/s with every batch slot live (models/paged_decode.py).

The reference has no serving story at all; this is the framework-level
number for the paged KV path — decode cost ∝ live context, memory ∝ tokens
in use.  Run:

    python -m benchmarks.serve_bench --slots 8 --context 2048

Prints one jsonl row per phase (prefill, decode) to --out and stdout.
"""

import argparse
import json
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--context", type=int, default=2048,
                    help="prompt tokens per slot")
    ap.add_argument("--decode-steps", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=2048)
    ap.add_argument("--n-layers", type=int, default=8)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--kv-heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--page", type=int, default=128)
    ap.add_argument("--quantize", action="store_true",
                    help="int8 page pools with per-token dequant scales")
    ap.add_argument("--churn", type=int, default=0,
                    help="N > 0: third phase — N requests (2x slots queue "
                         "depth) through the ServeEngine with staggered "
                         "budgets, measuring end-to-end tokens/s including "
                         "admission/retirement churn")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="churn phase: share one --context/2 token prefix "
                         "across all requests and serve with automatic "
                         "prefix caching")
    ap.add_argument("--spec-layers", type=int, default=0,
                    help="N > 0: speculative churn phase with an early-exit "
                         "self-draft (the target's first N layers, weights "
                         "shared — LayerSkip-style, no separate draft "
                         "training).  Records acceptance rate and tokens/s "
                         "against the plain engine on the same workload; "
                         "with an UNTRAINED target the acceptance (and so "
                         "the speedup) is expected to be poor — the row is "
                         "the harness evidence + the honest number, not a "
                         "claim")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per speculative round")
    ap.add_argument("--dense-baseline", action="store_true",
                    help="extra phase: dense KV-cache decode at the same "
                         "(slots, context) — the paged path's comparison "
                         "point (dense cost ∝ max_seq, paged ∝ live "
                         "context)")
    ap.add_argument("--out", default="results/serve.jsonl")
    args = ap.parse_args(argv)
    if args.spec_layers >= args.n_layers:
        # validate BEFORE any phase runs — failing after minutes of TPU
        # prefill/decode benchmarking would waste the whole invocation
        raise SystemExit("--spec-layers must be < --n-layers")

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print("serve_bench: not on TPU; refusing to record numbers",
              file=sys.stderr)
        sys.exit(1)

    from burst_attn_tpu.models import ModelConfig, init_params
    from burst_attn_tpu.models.paged_decode import (
        init_paged_state, paged_decode_step, paged_prefill, provision_capacity,
    )

    cfg = ModelConfig(
        vocab=args.vocab, d_model=args.d_model, n_layers=args.n_layers,
        n_heads=args.n_heads, n_kv_heads=args.kv_heads,
        d_head=args.d_model // args.n_heads, d_ff=4 * args.d_model,
        batch_axis=None, head_axis=None,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    # +1: the warm-up/compile decode step appends a token per slot too
    pages_per_seq = -(-(args.context + args.decode_steps + 1) // args.page)
    n_pages = args.slots * pages_per_seq + 2
    state, pool = init_paged_state(
        cfg, slots=args.slots, n_pages=n_pages, page=args.page,
        max_pages_per_seq=pages_per_seq, quantize=args.quantize)

    def record(row):
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)

    key = jax.random.PRNGKey(1)
    prompts = jax.random.randint(key, (args.slots, args.context), 1, cfg.vocab)

    # admit every slot; time the LAST prefill (compile amortized by the
    # first).  With --slots 1 the single slot is retired and re-prefilled
    # so the timed number never embeds the compile.
    from burst_attn_tpu.models.paged_decode import retire_slot

    t0 = time.perf_counter()
    logits, state = paged_prefill(params, prompts[0], state, pool, 0, cfg)
    jax.block_until_ready(logits)
    compile_s = time.perf_counter() - t0
    if args.slots == 1:
        state = retire_slot(state, pool, 0)
        t0 = time.perf_counter()
        logits, state = paged_prefill(params, prompts[0], state, pool, 0, cfg)
        jax.block_until_ready(logits)
        prefill_s = time.perf_counter() - t0
    for slot in range(1, args.slots):
        t0 = time.perf_counter()
        logits, state = paged_prefill(params, prompts[slot], state, pool,
                                      slot, cfg)
        jax.block_until_ready(logits)
        prefill_s = time.perf_counter() - t0
    record({"phase": "prefill", "context": args.context, "slots": args.slots,
            "quantize": args.quantize,
            "ms_per_prompt": round(prefill_s * 1e3, 2),
            "first_compile_s": round(compile_s, 1),
            "prefill_tokens_per_s": round(args.context / prefill_s, 1)})

    # steady-state decode: all slots advance per step.  Pages for the whole
    # decode run are provisioned OUTSIDE the timed loop — per-step
    # ensure_capacity calls would each sync a device length to host (slots
    # blocking transfers per step) and pollute step_ms with host overhead.
    tokens = jnp.ones((args.slots,), jnp.int32)
    for s in range(args.slots):
        state = provision_capacity(state, pool, s, args.decode_steps + 1)
    lg, state = paged_decode_step(params, tokens, state, cfg)  # compile
    jax.block_until_ready(lg)
    n_timed = args.decode_steps
    t0 = time.perf_counter()
    for _ in range(n_timed):
        lg, state = paged_decode_step(params, tokens, state, cfg)
    jax.block_until_ready(lg)
    dt = (time.perf_counter() - t0) / n_timed
    record({"phase": "decode", "context": args.context, "slots": args.slots,
            "quantize": args.quantize,
            "step_ms": round(dt * 1e3, 2),
            "tokens_per_s": round(args.slots / dt, 1)})

    if args.dense_baseline:
        # dense KV-cache decode (models/decode.py): batch = slots, cache
        # sized context + decode budget.  Same timed-loop discipline as the
        # paged decode phase (async dispatches, one final block).
        from burst_attn_tpu.models.decode import forward_cached, prefill

        max_seq = args.context + args.decode_steps + 1
        d_logits, cache = prefill(params, prompts, cfg, max_seq)
        jax.block_until_ready(d_logits)
        # donate the cache like generate()'s scan carry does — an undonated
        # dense cache would add a full copy per step and unfairly slow the
        # baseline
        step = jax.jit(lambda p, t, pos, c: forward_cached(p, t, pos, c, cfg),
                       donate_argnums=(3,))
        tok1 = jnp.ones((args.slots, 1), jnp.int32)
        pos = jnp.full((args.slots, 1), args.context, jnp.int32)
        lg, cache = step(params, tok1, pos, cache)  # compile
        jax.block_until_ready(lg)
        t0 = time.perf_counter()
        for i in range(args.decode_steps):
            lg, cache = step(params, tok1, pos + 1 + i, cache)
        jax.block_until_ready(lg)
        dt = (time.perf_counter() - t0) / args.decode_steps
        record({"phase": "decode-dense", "context": args.context,
                "slots": args.slots, "max_seq": max_seq,
                "step_ms": round(dt * 1e3, 2),
                "tokens_per_s": round(args.slots / dt, 1)})

    def timed_engine_run(eng):
        """Warm one step outside the timed region (compiles + its tokens),
        then time eng.run(); returns (tokens_emitted, wall_s).  The ONE
        accounting used by every engine-level phase (churn, spec) so the
        warm-token methodology cannot drift between them."""
        eng.step()
        warm = (sum(len(r.tokens) for r in eng.slots if r is not None)
                + sum(len(v) for v in eng.results().values()))
        t0 = time.perf_counter()
        out = eng.run()
        wall = time.perf_counter() - t0
        return sum(len(v) for v in out.values()) - warm, wall

    if args.churn > 0:
        # end-to-end engine throughput WITH request turnover: staggered
        # budgets force continuous retirement + admission, the regime a
        # server actually runs in (the decode phase above is the
        # steady-state upper bound)
        import numpy as np

        from burst_attn_tpu.models.serve import ServeEngine

        state = None  # free the phase-1/2 pools before allocating the engine's
        n_req = args.churn
        budgets = [args.decode_steps // 2 + (i % 4) * (args.decode_steps // 4)
                   for i in range(n_req)]
        pages_per_req = -(-(args.context + max(budgets)) // args.page)
        # prefix-cache mode needs pool headroom for the cached prefix pages
        extra = (args.context // 2 // args.page + 2) if args.prefix_cache else 0
        eng = ServeEngine(
            params, cfg, slots=args.slots,
            n_pages=args.slots * pages_per_req + 2 + extra, page=args.page,
            max_pages_per_seq=pages_per_req, quantize=args.quantize,
            prefix_cache=args.prefix_cache)
        rng = np.random.RandomState(0)
        # draw the shared prefix ONLY in prefix-cache mode: consuming RNG
        # state unconditionally would shift plain-churn prompt streams and
        # break comparability with previously recorded rows
        shared = (rng.randint(1, cfg.vocab, args.context // 2)
                  if args.prefix_cache else None)
        for i in range(n_req):
            if args.prefix_cache:
                prompt = np.concatenate(
                    [shared, rng.randint(1, cfg.vocab,
                                         args.context - len(shared))])
            else:
                prompt = rng.randint(1, cfg.vocab, args.context)
            eng.submit(prompt, budgets[i])
        total, wall = timed_engine_run(eng)
        record({"phase": "churn", "requests": n_req, "slots": args.slots,
                "context": args.context, "quantize": args.quantize,
                "prefix_cache": args.prefix_cache,
                "total_tokens": total, "wall_s": round(wall, 2),
                "tokens_per_s": round(total / wall, 1)})
        eng = None  # free the churn pools before any spec-phase engines

    if args.spec_layers > 0:
        # speculative vs plain on the SAME workload, early-exit self-draft
        # (target's first N layers, weights shared).  tokens/s + acceptance
        # are recorded as measured; the break-even note makes the row
        # interpretable either way (an untrained target's early-exit
        # acceptance is expected to be low — the harness and the accounting
        # are the deliverable, the speedup needs a trained model).
        import dataclasses

        import numpy as np

        from burst_attn_tpu.models.serve import ServeEngine

        state = None  # free the phase-1/2 pools (if churn didn't already)
        dcfg = dataclasses.replace(cfg, n_layers=args.spec_layers)
        dparams = dict(params, layers=params["layers"][: args.spec_layers])
        n_req = 2 * args.slots
        pages_per_req = -(-(args.context + args.decode_steps
                            + args.spec_k + 1) // args.page)
        n_pages = args.slots * pages_per_req + 2
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, cfg.vocab, args.context)
                   for _ in range(n_req)]

        def run_engine(spec):
            kw = dict(draft_params=dparams, draft_cfg=dcfg,
                      spec_k=args.spec_k) if spec else {}
            eng = ServeEngine(params, cfg, slots=args.slots, n_pages=n_pages,
                              page=args.page, max_pages_per_seq=pages_per_req,
                              quantize=args.quantize, **kw)
            for p in prompts:
                eng.submit(p, args.decode_steps)
            toks, wall = timed_engine_run(eng)
            return toks / wall, eng

        plain_tps, plain_eng = run_engine(False)
        del plain_eng  # free its pools before the spec target+draft pair
        spec_tps, eng = run_engine(True)
        record({"phase": "spec", "slots": args.slots,
                "context": args.context, "quantize": args.quantize,
                "spec_k": args.spec_k,
                "draft_layers": args.spec_layers, "n_layers": args.n_layers,
                "acceptance_rate": round(eng.acceptance_rate or 0.0, 3),
                "spec_rounds": eng.spec_rounds,
                "plain_tokens_per_s": round(plain_tps, 1),
                "spec_tokens_per_s": round(spec_tps, 1),
                "speedup": round(spec_tps / plain_tps, 3)})


if __name__ == "__main__":
    main()
