"""Benchmark harness — the reference's benchmarks/benchmark.py rebuilt.

Same FLOPs convention (reference benchmark.py:17-24): fwd FLOPs =
4*b*s^2*n*d / (2 if causal), bwd = 2.5x, fwd+bwd = 3.5x; TFLOPs/s divided by
ring width for distributed methods -> per-chip numbers comparable with the
reference README tables (SURVEY.md §6).  Results append to a jsonl file
(reference utils.py:73-86).

Methods (reference benchmark.py:146-153, get_burst_func :242):
  flash         — single-chip Pallas flash attention over the full sequence
  burst         — burst_attn, zigzag layout
  burst_striped — burst_attn, striped layout
  ring          — score-materializing ring baseline (benchmarks/ring_baseline)

Usage:  python -m benchmarks.benchmark [--methods burst,flash] [--seqs 4096]
        [--mesh 8 | --mesh 2x4] [--causal] [--double-ring] [--out results.jsonl]
"""

import argparse
import json
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def flops(b, s, n, d, mode="fwd", causal=False):
    f = 4 * b * s * s * n * d / (2 if causal else 1)
    return {"fwd": f, "bwd": 2.5 * f, "fwd_bwd": 3.5 * f}[mode]


def efficiency(flop, t):
    return flop / t / 1e12


def bench_fn(fn, *args, warmup=3, iters=10, reps=3, on_event=None):
    """fn must return a SCALAR.  All `iters` dispatches are queued
    asynchronously and synchronized by ONE host fetch of their sum — a
    per-iteration fetch would add the host<->device round trip (tens of ms
    through the axon-relay TPU tunnel) to every measurement.

    `on_event(phase, **fields)`: optional progress hook (bench.py's
    incremental JSONL log) fired at compile start/end, after each warmup
    call, and after each rep — a run killed by a stage timeout then still
    leaves per-phase timestamps behind."""
    ev = on_event if on_event is not None else (lambda phase, **kw: None)
    ev("compile_start")
    float(fn(*args))  # first call compiles (or replays the compile cache)
    ev("compile_end")
    for i in range(1, warmup):
        float(fn(*args))
        ev("warmup", i=i)
    ts = []
    for r in range(reps):
        t0 = time.perf_counter()
        acc = None
        for _ in range(iters):
            res = fn(*args)
            acc = res if acc is None else acc + res
        float(acc)
        ts.append((time.perf_counter() - t0) / iters)
        ev("rep", i=r, s_per_iter=round(ts[-1], 6))
    return float(np.min(ts))


def time_flash_fwd(b, n, s, d, *, block_q, block_kv, block_kv_compute=None,
                   n_kv=None, triangular=True, empty_carry=False, **fwd_kw):
    """Time ONE raw flash_fwd config on fresh bf16 inputs — the
    kernel-sweep scaffold shared by sweep_blocks (--fwd-loop/--ablate-fwd)
    and batch_probe (nosoftmax rows), so the two probes cannot silently
    drift apart.  Returns (seconds, fwd TFLOPs/s).  fwd_kw passes through
    to flash_fwd (loop_sweep=True, _ablate="nosoftmax", ...).

    empty_carry=True times the None-carry fast path (what the single-device
    flash_attention forward runs); the default times a carried state, which
    is what every ring round after the first pays."""
    from burst_attn_tpu.ops.masks import round_spec
    from burst_attn_tpu.ops.pallas_flash import flash_fwd
    from burst_attn_tpu.ops.tile import init_state

    n_kv = n_kv or n
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, n, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, n_kv, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, n_kv, s, d), jnp.bfloat16)
    spec = round_spec(jnp.int32(0), jnp.int32(0), s, s, True, "contig")
    st = (None, None, None) if empty_carry else init_state(b, n, s, d)
    f = jax.jit(lambda q, k, v: jnp.sum(flash_fwd(
        q, k, v, *st, d**-0.5, spec,
        block_q=block_q, block_kv=block_kv,
        block_kv_compute=block_kv_compute, triangular=triangular,
        **fwd_kw)[2]))
    t = bench_fn(f, q, k, v)
    return t, flops(b, s, n, d, "fwd", True) / t / 1e12


def _scalar_grads(grads):
    return sum(jnp.sum(g.astype(jnp.float32)) for g in grads)


def make_mesh(spec: str):
    devs = jax.devices()
    if "x" in spec:
        inter, intra = (int(x) for x in spec.split("x"))
        if inter * intra > len(devs):
            raise SystemExit(f"mesh {spec} needs {inter*intra} devices, have {len(devs)}")
        mesh = Mesh(np.array(devs[: inter * intra]).reshape(inter, intra), ("inter", "intra"))
        return mesh, ("inter", "intra")
    w = int(spec)
    return Mesh(np.array(devs[:w]), ("sp",)), ("sp",)


def run_method(method, mesh, seq_axes, b, s, n, d, n_kv, causal, dtype, backend,
               fwd_only=False):
    from burst_attn_tpu import burst_attn
    from burst_attn_tpu.parallel import layouts

    w = int(np.prod([mesh.shape[a] for a in seq_axes]))
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)

    if method == "flash":
        # full sequence on ONE chip (reference benchmark.py:146-153)
        from burst_attn_tpu.ops.pallas_flash import flash_attention

        q = jax.random.normal(kq, (b, n, s, d), dtype)
        k = jax.random.normal(kk, (b, n_kv, s, d), dtype)
        v = jax.random.normal(kv, (b, n_kv, s, d), dtype)
        fwd = jax.jit(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, None, causal).astype(jnp.float32)))
        if fwd_only:
            # at the longest sequences the bwd residuals don't fit one chip;
            # fwd-only still anchors the TFLOPs scaling curve (BASELINE.md)
            return bench_fn(fwd, q, k, v), None, 1
        do = jax.random.normal(kg, (b, n, s, d), dtype)

        # NB: big arrays (do) must be jit ARGUMENTS, not closures — a closed-
        # over array is embedded in the compile payload (multi-hundred-MB
        # requests overflow the remote-compile tunnel)
        @jax.jit
        def fb(q, k, v, do):
            def loss(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, None, causal).astype(jnp.float32)
                    * do.astype(jnp.float32))
            return _scalar_grads(jax.grad(loss, argnums=(0, 1, 2))(q, k, v))

        return bench_fn(fwd, q, k, v), bench_fn(fb, q, k, v, do), 1

    layout = {"burst": "zigzag", "burst_striped": "striped", "ring": "contig"}[method]
    seq_spec = seq_axes if len(seq_axes) > 1 else seq_axes[0]
    shard = NamedSharding(mesh, P(None, None, seq_spec, None))
    q = jax.device_put(jax.random.normal(kq, (b, n, s, d), dtype), shard)
    k = jax.device_put(jax.random.normal(kk, (b, n_kv, s, d), dtype), shard)
    v = jax.device_put(jax.random.normal(kv, (b, n_kv, s, d), dtype), shard)
    # the gradient seed is only materialized when the bwd actually runs —
    # fwd-only exists for configs where one more q-sized buffer OOMs
    do = (None if fwd_only
          else jax.device_put(jax.random.normal(kg, (b, n, s, d), dtype), shard))

    if method == "ring":
        from benchmarks.ring_baseline import ring_attention

        if len(seq_axes) != 1:
            raise SystemExit("ring baseline supports a single 'sp' axis only")
        fwd = jax.jit(
            lambda q, k, v: jnp.sum(
                ring_attention(q, k, v, mesh=mesh, causal=causal).astype(jnp.float32)))
        if fwd_only:
            return bench_fn(fwd, q, k, v), None, w

        @jax.jit
        def fb(q, k, v, do):
            def loss(q, k, v):
                o = ring_attention(q, k, v, mesh=mesh, causal=causal)
                return jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32))
            return _scalar_grads(jax.grad(loss, argnums=(0, 1, 2))(q, k, v))

        return bench_fn(fwd, q, k, v), bench_fn(fb, q, k, v, do), w

    attn = partial(
        burst_attn, mesh=mesh, seq_axes=seq_axes, causal=causal, layout=layout,
        backend=backend,
    )
    fwd = jax.jit(lambda q, k, v: jnp.sum(attn(q, k, v).astype(jnp.float32)))
    if fwd_only:
        return bench_fn(fwd, q, k, v), None, w

    @jax.jit
    def fb(q, k, v, do):
        def loss(q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32) * do.astype(jnp.float32))
        return _scalar_grads(jax.grad(loss, argnums=(0, 1, 2))(q, k, v))

    return bench_fn(fwd, q, k, v), bench_fn(fb, q, k, v, do), w


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--methods", default="burst,flash")
    ap.add_argument("--seqs", default="4096")
    ap.add_argument("--mesh", default=str(len(jax.devices())))
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--kv-heads", type=int, default=None)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--causal", action="store_true")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--fwd-only", action="store_true",
                    help="skip the fwd+bwd timing (longest seqs OOM the bwd)")
    ap.add_argument("--out", default="results/results.jsonl")
    args = ap.parse_args()

    import os

    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
    mesh, seq_axes = make_mesh(args.mesh)
    dtype = jnp.dtype(args.dtype)
    n_kv = args.kv_heads or args.heads
    for s in (int(x) for x in args.seqs.split(",")):
        for method in args.methods.split(","):
            t_f, t_fb, w = run_method(
                method, mesh, seq_axes, args.batch, s, args.heads, args.dim,
                n_kv, args.causal, dtype, args.backend,
                fwd_only=args.fwd_only,
            )
            rec = {
                "method": method, "seq": s, "batch": args.batch,
                "heads": args.heads, "kv_heads": n_kv, "dim": args.dim,
                "causal": args.causal, "dtype": str(dtype), "world": w,
                "fwd_ms": round(t_f * 1e3, 3),
                "fwd_tflops_per_chip": round(
                    efficiency(flops(args.batch, s, args.heads, args.dim, "fwd", args.causal), t_f) / w, 2),
            }
            if t_fb is not None:
                rec["fwd_bwd_ms"] = round(t_fb * 1e3, 3)
                rec["fwd_bwd_tflops_per_chip"] = round(
                    efficiency(flops(args.batch, s, args.heads, args.dim, "fwd_bwd", args.causal), t_fb) / w, 2)
            print(json.dumps(rec))
            # append per record: an interrupted multi-config run (tunnel
            # drop mid-sweep) keeps what it measured
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
