"""End-to-end train-step MFU smoke on real hardware (round-1 verdict item 3).

Trains the flagship LM on synthetic data for a few steps on the real chip,
reports tokens/s + MFU, and captures an XLA profile — the kernel-occupancy /
pipelining evidence the reference never had (its benchmarks stop at the op).

MFU convention: model FLOPs/token = 6 * n_params  (fwd+bwd dense matmuls)
              + 12 * n_layers * s * d_head * n_heads / (2 if causal)
              (attention scores+pv, fwd+bwd at 2x+... folded into the 12x;
              causal halves the live score area), against the chip's peak
              bf16 TFLOPs (v5e: 197).

    python -m benchmarks.train_smoke --steps 8 --seq 32768 \
        --trace-dir /root/repo/results/trace_smoke
"""

import argparse
import json
import sys

import numpy as np


# keyed by ops/tuning.canonical_kind so device-kind strings are interpreted
# in exactly one place
PEAK_BF16 = {"v5e": 197e12, "v4": 275e12, "v5p": 459e12, "v6": 918e12}


def peak_flops(device):
    """(peak bf16 FLOPs/s, known) — falls back to the v5e peak for an
    unrecognized generation, flagged so the recorded MFU is not mistaken
    for a calibrated number."""
    from burst_attn_tpu.ops.tuning import canonical_kind

    kind = canonical_kind(device)
    if kind in PEAK_BF16:
        return PEAK_BF16[kind], True
    print(f"train_smoke: unrecognized device kind "
          f"{getattr(device, 'device_kind', '?')!r}; MFU uses the v5e peak",
          file=sys.stderr)
    return 197e12, False


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--seq", type=int, default=32768)
    ap.add_argument("--d-model", type=int, default=2048)
    ap.add_argument("--n-layers", type=int, default=16)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32768)
    ap.add_argument("--trace-dir", default=None,
                    help="capture an XLA profile of the traced steps here")
    ap.add_argument("--trace-steps", type=int, default=2)
    ap.add_argument("--out", default="results/results_smoke.jsonl")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print("train_smoke: not on TPU; refusing to record numbers",
              file=sys.stderr)
        sys.exit(1)

    from burst_attn_tpu.models import ModelConfig
    from burst_attn_tpu.models.train import (
        TrainConfig, init_train_state, make_batch, make_mesh, make_train_step,
    )
    from burst_attn_tpu.utils.profiling import StepTimer

    cfg = ModelConfig(
        vocab=args.vocab, d_model=args.d_model, n_layers=args.n_layers,
        n_heads=args.n_heads, n_kv_heads=args.n_heads,
        d_head=args.d_model // args.n_heads, d_ff=4 * args.d_model,
        batch_axis=None, head_axis=None, seq_axes=("sp",), remat=True,
    )
    mesh = make_mesh({"sp": 1}, devices=jax.devices()[:1])
    tcfg = TrainConfig()
    state = init_train_state(jax.random.PRNGKey(0), cfg, tcfg, mesh)
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(state[0]))
    step = make_train_step(cfg, tcfg, mesh)
    batch = make_batch(jax.random.PRNGKey(1), cfg, mesh, batch=args.batch,
                       seq=args.seq)

    # at least one warmup: the first call compiles, and `metrics` must be
    # bound before the sync below
    for _ in range(max(1, args.warmup)):
        state, metrics = step(state, batch)
    float(metrics["loss"])  # sync

    timer = StepTimer()
    for _ in range(args.steps):
        with timer:
            state, metrics = step(state, batch)
            timer.watch(metrics["loss"])
    loss = float(metrics["loss"])

    if args.trace_dir:
        with jax.profiler.trace(args.trace_dir):
            for _ in range(args.trace_steps):
                state, metrics = step(state, batch)
            float(metrics["loss"])

    tokens = args.batch * args.seq
    step_s = min(timer.times)  # best step; summary() has the spread
    tok_per_s = tokens / step_s
    # fwd+bwd matmul FLOPs: 6 FLOPs/param/token; attention: s^2*d*n scores +
    # pv = 4*s^2*n*d per layer fwd (/2 causal), x3.5 fwd+bwd
    attn_flops = (args.n_layers * 3.5 * 4 * args.batch * args.seq * args.seq
                  * args.n_heads * (args.d_model // args.n_heads) / 2)
    flops_step = 6.0 * n_params * tokens + attn_flops
    dev = jax.devices()[0]
    peak, peak_known = peak_flops(dev)
    mfu = flops_step / step_s / peak
    rec = {
        "device": dev.device_kind, "params": n_params, "batch": args.batch,
        "seq": args.seq, "d_model": args.d_model, "n_layers": args.n_layers,
        "steps": args.steps, "loss": round(loss, 4),
        "step_ms": round(step_s * 1e3, 1),
        "tokens_per_s": round(tok_per_s, 1),
        "model_tflops_per_s": round(flops_step / step_s / 1e12, 1),
        "mfu": round(mfu, 4),
        "peak_bf16_tflops": peak / 1e12,
        "peak_extrapolated": not peak_known,
        "trace_dir": args.trace_dir,
    }
    print(json.dumps(rec))
    with open(args.out, "a") as f:
        f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
