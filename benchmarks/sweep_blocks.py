"""Kernel block-size sweep on real TPU — finds the fwd/bwd block optimum
that bench.py's defaults should use.

Each fresh kernel shape is a 5-10 MINUTE remote compile through the axon
tunnel; results append to a jsonl file immediately so an interrupted sweep
keeps what it measured.  Run in the background:

    python -m benchmarks.sweep_blocks --out /tmp/sweep.jsonl
"""

import argparse
import json
import sys


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=65536)
    p.add_argument("--heads", type=int, default=32)
    p.add_argument("--kv-heads", type=int, default=None)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--out", default="sweep_blocks.jsonl")
    p.add_argument("--fwd", default="2048x2048,2048x4096,1024x4096",
                   help="comma list of BQxBKV (fwd), empty to skip")
    p.add_argument("--bwd", default="1024x2048,1024x4096,2048x2048,512x4096",
                   help="comma list of BQxBKV (bwd), empty to skip")
    p.add_argument("--fwd-compute", default="",
                   help="comma list of BQxBKVxBKC (fwd with compute sub-block)")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from benchmarks.benchmark import bench_fn, flops
    from burst_attn_tpu.ops.pallas_flash import flash_attention

    if jax.default_backend() != "tpu":
        print("sweep_blocks: not on TPU; refusing to record numbers", file=sys.stderr)
        sys.exit(1)

    b, n, d, seq = 1, args.heads, args.dim, args.seq
    nkv = args.kv_heads or n
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, n, seq, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, nkv, seq, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, nkv, seq, d), jnp.bfloat16)
    do = jax.random.normal(kg, (b, n, seq, d), jnp.bfloat16)

    def record(row):
        row.update(seq=seq, heads=n, kv_heads=nkv, dim=d)
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)

    def parse(spec):
        return [tuple(int(x) for x in c.split("x")) for c in spec.split(",") if c]

    for cfg in parse(args.fwd) + parse(args.fwd_compute):
        bq, bkv = cfg[0], cfg[1]
        bkc = cfg[2] if len(cfg) > 2 else None
        try:
            f = jax.jit(lambda q, k, v, bq=bq, bkv=bkv, bkc=bkc: jnp.sum(
                flash_attention(q, k, v, None, True, bq, bkv,
                                block_kv_compute=bkc).astype(jnp.float32)))
            t = bench_fn(f, q, k, v)
            record({"pass": "fwd", "bq": bq, "bkv": bkv, "bkc": bkc,
                    "ms": round(t * 1e3, 2),
                    "tflops": round(flops(b, seq, n, d, "fwd", True) / t / 1e12, 1)})
        except Exception as e:  # noqa: BLE001 - record and continue the sweep
            record({"pass": "fwd", "bq": bq, "bkv": bkv, "bkc": bkc,
                    "error": f"{type(e).__name__}: {e}"[:200]})

    for bqb, bkvb in parse(args.bwd):
        try:
            @jax.jit
            def fb(q, k, v, do, bqb=bqb, bkvb=bkvb):
                def loss(q, k, v):
                    o = flash_attention(q, k, v, None, True, 2048, 2048, bqb, bkvb)
                    return jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32))
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
                return (jnp.sum(dq.astype(jnp.float32))
                        + jnp.sum(dk.astype(jnp.float32))
                        + jnp.sum(dv.astype(jnp.float32)))
            t = bench_fn(fb, q, k, v, do)
            record({"pass": "fwd+bwd", "bq_bwd": bqb, "bkv_bwd": bkvb,
                    "ms": round(t * 1e3, 2),
                    "tflops": round(flops(b, seq, n, d, "fwd_bwd", True) / t / 1e12, 1)})
        except Exception as e:  # noqa: BLE001
            record({"pass": "fwd+bwd", "bq_bwd": bqb, "bkv_bwd": bkvb,
                    "error": f"{type(e).__name__}: {e}"[:200]})


if __name__ == "__main__":
    main()
