"""Kernel block-size sweep on real TPU — finds the fwd/bwd block optimum
that bench.py's defaults should use.

Each fresh kernel shape is a 5-10 MINUTE remote compile through the axon
tunnel; results append to a jsonl file immediately so an interrupted sweep
keeps what it measured.  Run in the background:

    python -m benchmarks.sweep_blocks --out /tmp/sweep.jsonl
"""

import argparse
import json
import sys


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=65536)
    p.add_argument("--heads", type=int, default=32)
    p.add_argument("--kv-heads", type=int, default=None)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--out", default="results/sweep_blocks.jsonl")
    p.add_argument("--fwd", default="2048x2048,2048x4096,1024x4096",
                   help="comma list of BQxBKV (fwd), empty to skip")
    p.add_argument("--bwd", default="1024x2048,1024x4096,2048x2048,512x4096",
                   help="comma list of BQxBKV (bwd-only, fused kernel), "
                        "BQxBKVxsplit (split dq / dkdv kernels), or "
                        "BQxBKVxtri (wrapped-diagonal causal grid; optional "
                        "xBKC sub-block and xloop for the fori_loop sweep, "
                        "e.g. 1024x4096xtrix1024xloop); empty to skip")
    p.add_argument("--fwd-compute", default="",
                   help="comma list of BQxBKVxBKC (fwd with compute sub-block)")
    p.add_argument("--ablate-fwd", default="",
                   help="comma list of BQxBKV timed with the softmax chain "
                        "stripped (wrong numerics; measures the MXU/pipeline "
                        "ceiling to localize the fwd kernel's VPU cost)")
    p.add_argument("--fwd-loop", default="",
                   help="comma list of BQxBKVxBKC timed with the fori_loop "
                        "sub-block sweep (loop_sweep=True): buffers reuse "
                        "per iteration, probing whether the VMEM area cliff "
                        "is unrolled-stage liveness")
    p.add_argument("--fwd-raw-empty", default="",
                   help="comma list of BQxBKV[xBKC] timed through the RAW "
                        "flash_fwd scaffold with the None-carry fast path "
                        "(empty_carry=True) — isolates the carry-state DMA "
                        "cost vs the carried rows the same scaffold times "
                        "by default (--fwd already times the None-carry "
                        "path end-to-end through flash_attention)")
    args = p.parse_args()

    import os

    # sweeps measure whatever config they're told to, including past-cliff
    # ones (how the cliff law in ops/tuning.py was found in the first place)
    os.environ["BURST_ALLOW_CLIFF"] = "1"

    import jax
    import jax.numpy as jnp

    from benchmarks.benchmark import bench_fn, flops
    from burst_attn_tpu.ops.pallas_flash import flash_attention, tri_bwd_supported

    if jax.default_backend() != "tpu":
        print("sweep_blocks: not on TPU; refusing to record numbers", file=sys.stderr)
        sys.exit(1)

    b, n, d, seq = 1, args.heads, args.dim, args.seq
    nkv = args.kv_heads or n
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, n, seq, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, nkv, seq, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, nkv, seq, d), jnp.bfloat16)
    do = jax.random.normal(kg, (b, n, seq, d), jnp.bfloat16)

    def record(row):
        row.update(seq=seq, heads=n, kv_heads=nkv, dim=d)
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)

    def parse(spec):
        return [tuple(int(x) for x in c.split("x")) for c in spec.split(",") if c]

    for cfg in parse(args.fwd) + parse(args.fwd_compute):
        bq, bkv = cfg[0], cfg[1]
        bkc = cfg[2] if len(cfg) > 2 else None
        try:
            f = jax.jit(lambda q, k, v, bq=bq, bkv=bkv, bkc=bkc: jnp.sum(
                flash_attention(q, k, v, None, True, bq, bkv,
                                block_kv_compute=bkc).astype(jnp.float32)))
            t = bench_fn(f, q, k, v)
            record({"pass": "fwd", "bq": bq, "bkv": bkv, "bkc": bkc,
                    "ms": round(t * 1e3, 2),
                    "tflops": round(flops(b, seq, n, d, "fwd", True) / t / 1e12, 1)})
        except Exception as e:  # noqa: BLE001 - record and continue the sweep
            record({"pass": "fwd", "bq": bq, "bkv": bkv, "bkc": bkc,
                    "error": f"{type(e).__name__}: {e}"[:200]})

    def bench_flash_fwd(pass_name, cfgs, **fwd_kw):
        """Raw-flash_fwd timing modes (loop / ablation variants): one row
        per BQxBKV[xBKC] config via the scaffold shared with batch_probe
        (benchmarks.benchmark.time_flash_fwd)."""
        from benchmarks.benchmark import time_flash_fwd

        for cfg in cfgs:
            bq, bkv = cfg[0], cfg[1]
            bkc = cfg[2] if len(cfg) > 2 else None
            row = {"pass": pass_name, "bq": bq, "bkv": bkv, "bkc": bkc}
            try:
                t, tf = time_flash_fwd(b, n, seq, d, n_kv=nkv, block_q=bq,
                                       block_kv=bkv, block_kv_compute=bkc,
                                       **fwd_kw)
                row.update(ms=round(t * 1e3, 2), tflops=round(tf, 1))
            except Exception as e:  # noqa: BLE001
                row.update(error=f"{type(e).__name__}: {e}"[:200])
            record(row)

    bench_flash_fwd("fwd-loop", parse(args.fwd_loop), loop_sweep=True)
    bench_flash_fwd("fwd-ablate-nosoftmax", parse(args.ablate_fwd),
                    _ablate="nosoftmax")
    bench_flash_fwd("fwd-raw-empty", parse(args.fwd_raw_empty),
                    empty_carry=True)

    bwd_cfgs = [c for c in args.bwd.split(",") if c]
    if bwd_cfgs:
        # bwd-only timing isolates the kernel being tuned: one fwd run
        # provides the (lse, delta) inputs every bwd config reuses
        from burst_attn_tpu.ops.masks import round_spec
        from burst_attn_tpu.ops.pallas_flash import (
            _flash_attention_fwd_impl, flash_bwd,
        )

        scale = d**-0.5
        spec = round_spec(jnp.int32(0), jnp.int32(0), seq, seq, True, "contig")

        @jax.jit
        def prep(q, k, v, do):
            o, lse = _flash_attention_fwd_impl(q, k, v, None, True, 2048, 2048)
            delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), -1)
            return delta, lse

        try:
            delta, lse = jax.block_until_ready(prep(q, k, v, do))
        except Exception as e:  # noqa: BLE001 - record so the sweep's silence
            record({"pass": "bwd", "error": f"prep: {type(e).__name__}: {e}"[:200]})
            return

        for c in bwd_cfgs:
            parts = c.split("x")
            bqb, bkvb = int(parts[0]), int(parts[1])
            if len(parts) > 2 and parts[2] not in ("split", "tri"):
                record({"pass": "bwd", "error": f"bad config {c!r}: third "
                        "token must be 'split' or 'tri'"})
                continue
            fused = len(parts) <= 2 or parts[2] == "tri"
            tri = len(parts) > 2 and parts[2] == "tri"
            # optional trailing tokens (tri only, any order-tolerant mix):
            # a numeric compute sub-block and/or the literal 'loop' for the
            # fori_loop sweep, e.g. 1024x4096xtrix1024xloop.  Anything else
            # is an error ROW, not a sweep abort (a malformed token must
            # not cost the remaining multi-hour configs), and a misspelled
            # 'loop' must not silently time the unrolled kernel.
            bkc, loop, bad = None, False, None
            for tok in parts[3:]:
                if tok == "loop":
                    loop = True
                elif tok.isdigit():
                    bkc = int(tok)
                else:
                    bad = tok
            if bad is not None:
                record({"pass": "bwd", "error": f"bad config {c!r}: "
                        f"unknown token {bad!r} (want a number or 'loop')"})
                continue
            # record which kernel actually runs: flash_bwd silently falls
            # back to the rectangular fused kernel when the tri gate fails
            # (which also ignores loop_sweep — record the EFFECTIVE flags)
            tri_eff = tri and tri_bwd_supported(
                seq, seq, n, nkv, d, block_q=bqb, block_kv=bkvb,
                block_kv_compute=bkc)
            row = {"pass": "bwd", "bq_bwd": bqb, "bkv_bwd": bkvb,
                   "fused": fused, "tri": tri_eff, "bkc_bwd": bkc,
                   "loop": loop and tri_eff}
            if tri and not tri_eff:
                row["tri_requested_fell_back"] = True
            try:
                f = jax.jit(lambda q, k, v, do, delta, lse, bqb=bqb, bkvb=bkvb,
                            fused=fused, tri=tri, bkc=bkc, loop=loop: sum(
                    jnp.sum(g.astype(jnp.float32)) for g in flash_bwd(
                        do, q, k, v, delta, lse, scale, spec,
                        block_q=bqb, block_kv=bkvb, fused=fused, triangular=tri,
                        block_kv_compute=bkc, loop_sweep=loop)))
                t = bench_fn(f, q, k, v, do, delta, lse)
                row.update(ms=round(t * 1e3, 2),
                           tflops=round(flops(b, seq, n, d, "bwd", True) / t / 1e12, 1))
            except Exception as e:  # noqa: BLE001
                row.update(ms=None, error=f"{type(e).__name__}: {e}"[:200])
            record(row)


if __name__ == "__main__":
    main()
