"""Sliding-window attention perf: show cost scales with window, not seq.

Forward-only timing of flash_attention at fixed seq with shrinking
windows; with the band's dead-block skipping + DMA clamps, time should
drop roughly linearly in the window fraction (floor set by the q-side
pass).  Appends jsonl rows.

    python -m benchmarks.window_bench --seq 65536 --windows 65536,16384,4096
"""

import argparse
import json
import sys


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=65536)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--windows", default="65536,16384,4096",
                    help="comma list; 'none' = plain causal (tri grid)")
    ap.add_argument("--out", default="results/results_window.jsonl")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print("window_bench: not on TPU; refusing to record numbers",
              file=sys.stderr)
        sys.exit(1)

    from benchmarks.benchmark import bench_fn, flops
    from burst_attn_tpu.ops.pallas_flash import flash_attention

    b, n, d, s = 1, args.heads, args.dim, args.seq
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, n, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, n, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, n, s, d), jnp.bfloat16)

    for tok in args.windows.split(","):
        wnd = None if tok.strip().lower() == "none" else int(tok)
        fwd = jax.jit(lambda q, k, v, wnd=wnd: jnp.sum(
            flash_attention(q, k, v, None, True, window=wnd)
            .astype(jnp.float32)))
        t = bench_fn(fwd, q, k, v)
        # band-normalized TFLOPs: exact live-cell count (the causal band of
        # width w has s*w - w*(w-1)/2 cells — the first w rows ramp up), so
        # window == seq reproduces the causal convention instead of
        # double-counting the dead triangle
        if wnd is None:
            fl = flops(b, s, n, d, "fwd", True)
        else:
            w = min(wnd, s)
            fl = 4 * b * n * d * (s * w - w * (w - 1) / 2)
        rec = {"seq": s, "window": wnd, "fwd_ms": round(t * 1e3, 3),
               "band_tflops": round(fl / t / 1e12, 2)}
        print(json.dumps(rec), flush=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
