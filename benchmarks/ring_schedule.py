"""Ring comm/compute overlap evidence (round-2 verdict item 9).

XProf on a single chip cannot show ring overlap (W=1 has no permute), and
no multi-chip hardware is reachable — but the COMPILED SCHEDULE can be
inspected directly: XLA splits each ppermute into collective-permute-start
/ collective-permute-done, and the number of fusion/dot ops scheduled
BETWEEN start and done is exactly the compute the DMA overlaps.  This
script lowers one burst fwd(+bwd) step on a mesh, walks the optimized HLO
in schedule order, and reports, per collective-permute pair, how many
fused compute ops (and an estimate of their FLOPs share) sit inside the
in-flight window.

CPU (simulated 8-device mesh) runs everywhere:

    python -m benchmarks.ring_schedule --cpu --mesh 8 --seq 4096

On TPU (through the tunnel) the same lowering shows the real Mosaic/ICI
schedule; append --out to record the summary jsonl.
"""

import argparse
import json
import re
import sys


def analyze_hlo(hlo_text):
    """Parse optimized HLO text in (module, computation) order and pair
    collective-permute-start with its -done; count ops between them.

    XLA's latency-hiding scheduler emits instructions in schedule order
    inside each computation, so textual order between start and done is the
    overlap window.  Fusions containing dots are the MXU work."""
    pairs = []
    open_starts = {}  # name -> (line_idx, ops_between)
    compute_re = re.compile(r"^\s*\S+ = \S* (fusion|dot|convolution)\(")
    start_re = re.compile(r"^\s*(\S+) = \S* collective-permute-start\(")
    done_re = re.compile(r"^\s*\S+ = \S* collective-permute-done\(\s*(\S+?)\s*\)")
    for idx, line in enumerate(hlo_text.splitlines()):
        ms = start_re.match(line)
        if ms:
            open_starts[ms.group(1)] = [idx, 0]
            continue
        md = done_re.match(line)
        if md and md.group(1) in open_starts:
            start_idx, n_ops = open_starts.pop(md.group(1))
            pairs.append({"start_line": start_idx, "done_line": idx,
                          "compute_ops_inside": n_ops})
            continue
        if compute_re.match(line):
            for v in open_starts.values():
                v[1] += 1
    # synchronous collective-permute (no start/done split) = zero overlap
    sync = len(re.findall(r" collective-permute\(", hlo_text))
    return pairs, sync


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", default="8")
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--layout", default="zigzag")
    ap.add_argument("--bwd", action="store_true",
                    help="analyze the fwd+bwd step instead of fwd")
    ap.add_argument("--cpu", action="store_true",
                    help="force the simulated CPU mesh (8 host devices)")
    ap.add_argument("--out", default="")
    ap.add_argument("--dump-hlo", default="",
                    help="also write the full optimized HLO text here")
    args = ap.parse_args()

    import os

    world_req = 1
    for part in args.mesh.split("x"):
        world_req *= int(part)
    if args.cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={max(8, world_req)}")
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < world_req:
        # make_mesh's integer path silently builds a 1-device mesh — a W=1
        # "ring" has no permute at all and would record a misleading
        # zero-overlap row.  Refuse instead.
        sys.exit(f"ring_schedule: mesh {args.mesh} needs {world_req} devices, "
                 f"have {len(jax.devices())} ({jax.default_backend()}); "
                 "pass --cpu for a simulated host-device mesh")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from burst_attn_tpu import burst_attn
    from burst_attn_tpu.parallel import layouts

    from benchmarks.benchmark import make_mesh

    mesh, seq_axes = make_mesh(args.mesh)
    w = int(np.prod([mesh.shape[a] for a in seq_axes]))
    b, n, s, d = 1, args.heads, args.seq, args.dim
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    spec = P(None, None, seq_axes if len(seq_axes) > 1 else seq_axes[0], None)
    shard = NamedSharding(mesh, spec)
    q, k, v, do = (jax.device_put(
        layouts.to_layout(jax.random.normal(kk, (b, n, s, d), jnp.bfloat16),
                          args.layout, w, 2), shard) for kk in ks)

    def fwd(q, k, v):
        return jnp.sum(burst_attn(q, k, v, mesh=mesh, seq_axes=seq_axes,
                                  causal=True, layout=args.layout)
                       .astype(jnp.float32))

    if args.bwd:
        def step(q, k, v, do):
            def loss(q, k, v):
                o = burst_attn(q, k, v, mesh=mesh, seq_axes=seq_axes,
                               causal=True, layout=args.layout)
                return jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32))
            gs = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return sum(jnp.sum(g.astype(jnp.float32)) for g in gs)
        compiled = jax.jit(step).lower(q, k, v, do).compile()
    else:
        compiled = jax.jit(fwd).lower(q, k, v).compile()
    hlo = compiled.as_text()
    if args.dump_hlo:
        with open(args.dump_hlo, "w") as f:
            f.write(hlo)
    pairs, sync = analyze_hlo(hlo)
    overlapped = sum(1 for p in pairs if p["compute_ops_inside"] > 0)
    summary = {
        "backend": jax.default_backend(),
        "mesh": args.mesh, "layout": args.layout, "world": w,
        "seq": s, "bwd": args.bwd,
        "async_permute_pairs": len(pairs),
        "pairs_with_compute_inside": overlapped,
        "sync_permutes": sync,
        "ops_inside_per_pair": [p["compute_ops_inside"] for p in pairs],
    }
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(summary) + "\n")


if __name__ == "__main__":
    main()
