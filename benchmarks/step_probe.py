"""Grid-step overhead decomposition probe (round 4).

The 64K fwd measures ~13.1 us/grid-step against ~5.5 us of MXU work and
~1.2 us of K/V DMA at 819 GB/s — leaving ~5-6 us/step unexplained even
with the whole softmax chain ablated (nosoftmax floor 12.2 us/step).
This probe times a MINIMAL pallas kernel — per step: fetch one kv-sized
block and run one matmul into scratch, nothing else — across step counts
and block sizes, to split the per-step cost into

    t_step = t_fixed + bytes/bw + flops/mxu

If t_fixed dominates (per-step cost barely moves with block bytes), the
production kernel's ceiling really is Mosaic per-step sequencing and only
a step-count reduction (the VMEM-cliff break) can move the headline; if
the bytes term dominates, tall-q-style DMA shaping matters too.

    python -m benchmarks.step_probe --out results/step_probe.jsonl
"""

import argparse
import functools
import json
import os
import sys
from burst_attn_tpu.utils.compat import tpu_compiler_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--bq", type=int, default=2048,
                    help="rows of the resident block the matmul feeds")
    ap.add_argument("--kv-blocks", default="256,1024,2048,4096",
                    help="comma list of kv block heights (bytes scale)")
    ap.add_argument("--steps", default="512,2048,8192",
                    help="comma list of grid lengths (fixed-cost scale)")
    ap.add_argument("--no-matmul", action="store_true",
                    help="DMA-only variant (drop the MXU term entirely)")
    ap.add_argument("--out", default="results/step_probe.jsonl")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from benchmarks.benchmark import bench_fn

    if jax.default_backend() != "tpu":
        print("step_probe: not on TPU; refusing to record numbers",
              file=sys.stderr)
        sys.exit(1)
    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)

    def record(row):
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)

    d, bq = args.dim, args.bq

    def kernel(q_ref, k_ref, o_ref, acc, *, do_mm):
        j = pl.program_id(0)

        @pl.when(j == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)

        if do_mm:
            w = min(acc.shape[1], k_ref.shape[1])  # static
            acc[:, :w] = acc[:, :w] + jax.lax.dot_general(
                q_ref[0, :, :], k_ref[0, :, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )[:, :w]

        @pl.when(j == pl.num_programs(0) - 1)
        def _fin():
            o_ref[0, :, :] = acc[:]

    for bkv in (int(x) for x in args.kv_blocks.split(",") if x):
        for n_steps in (int(x) for x in args.steps.split(",") if x):
            # one kv block per step, streamed from a CAPPED pool addressed
            # j % n_pool — the index changes every step so the DMA always
            # re-issues, but HBM stays bounded for any step count (an
            # uncapped [n_steps, bkv, d] pool is 8.6 GB at 4096x8192);
            # q stays resident (constant index map)
            n_pool = min(n_steps, 512)
            do_mm = not args.no_matmul
            try:
                q = jax.random.normal(jax.random.PRNGKey(0), (1, bq, d),
                                      jnp.bfloat16)
                kpool = jax.random.normal(jax.random.PRNGKey(1),
                                          (n_pool, bkv, d), jnp.bfloat16)
                fn = pl.pallas_call(
                    functools.partial(kernel, do_mm=do_mm),
                    grid=(n_steps,),
                    in_specs=[
                        pl.BlockSpec((1, bq, d), lambda j: (0, 0, 0)),
                        pl.BlockSpec((1, bkv, d),
                                     lambda j, n_pool=n_pool: (j % n_pool, 0, 0)),
                    ],
                    out_specs=pl.BlockSpec((1, bq, 128), lambda j: (0, 0, 0)),
                    out_shape=jax.ShapeDtypeStruct((1, bq, 128), jnp.float32),
                    scratch_shapes=[pltpu.VMEM((bq, 128), jnp.float32)],
                    compiler_params=tpu_compiler_params(
                        dimension_semantics=("arbitrary",),
                    ),
                )
                f = jax.jit(lambda q, kp: jnp.sum(fn(q, kp)))
                t = bench_fn(f, q, kpool)
                step_us = t * 1e6 / n_steps
                mb = bkv * d * 2 / 1e6
                record({"bq": bq, "bkv": bkv, "steps": n_steps,
                        "matmul": do_mm, "ms": round(t * 1e3, 3),
                        "us_per_step": round(step_us, 3),
                        "kv_mb_per_step": round(mb, 3),
                        # residual after the 819 GB/s bytes term
                        "us_minus_dma": round(step_us - mb / 819 * 1e3, 3)})
            except Exception as e:  # noqa: BLE001 — record and continue
                record({"bq": bq, "bkv": bkv, "steps": n_steps,
                        "matmul": do_mm,
                        "error": f"{type(e).__name__}: {e}"[:200]})


if __name__ == "__main__":
    main()
