"""Ring-overlap microbenchmark: scan+ppermute ring vs the fused RDMA kernel.

Measures, per (seq, layout, pass) config on the real ring mesh:

  t_scan     — the scan-based ring (`backend="pallas"` per-round
               pallas_call + lax.ppermute; overlap is whatever XLA's async
               collective scheduling achieves)
  t_fused    — the fused single-kernel ring (`backend="fused_ring"`:
               in-kernel RDMA rotation — KV for the forward,
               ops/fused_ring.py; q-side bundle + concurrent dq ring for
               the backward, ops/fused_ring_bwd.py)
  t_compute  — compute-only floor: the same W rounds of tile compute with
               the ring rotation REMOVED (every round re-reads the resident
               local operands; identical kernel launches, masks and state
               carry, zero inter-chip traffic)
  t_comm     — comm-only floor: just the rotations (fwd: W-1 k/v permutes;
               bwd: W-1 bundle permutes + the W dq add-and-forward hops),
               no attention compute

and derives the achieved overlap fraction

  overlap = (t_compute + t_comm - t_ring) / min(t_compute, t_comm)

(1.0 = the smaller phase is fully hidden behind the larger; 0.0 = fully
serialized), plus the ideal-floor ratio t_ring / max(t_compute, t_comm).
One JSON line per (config, pass) appends to results/ring_overlap.jsonl,
each tagged with its `pass` ("fwd" | "bwd" | "fwd+bwd"; the combined pass
times one value_and_grad program and reports no floors — its floors are
the sum of the per-pass ones).

On a CPU host this still runs a tiny smoke config through the interpreted
fused kernels (BURST_FUSED_INTERPRET=1 is set for the fused legs) so the
harness itself is testable anywhere; the numbers are only meaningful on a
TPU ring.

Usage:  python -m benchmarks.ring_overlap [--seqs 16384,65536]
        [--mesh 8] [--layout zigzag] [--heads 32] [--dim 128]
        [--pass fwd|bwd|fwd+bwd|all] [--topology uni|bidi|double|all]
        [--window W] [--wire-dtype fp32|int8|fp8]
        [--out results/ring_overlap.jsonl]

--window W dispatches the occupancy-elided contig schedule
(docs/schedule_ir.md "Occupancy compilation"): both ring legs run the
r_live-round program, the floors are measured at r_live rounds/hops, and
the row additionally records the DENSE full-ring floors
(t_comm_dense_s / t_compute_dense_s) — the comm and compute the
dead-round elision removed.

--topology selects the compiled fused-ring schedule (parallel/schedule.py):
"bidi" runs the counter-rotating ring and also records the per-direction
comm floors (t_comm_uni_s vs the split t_comm_only_s — the reclaimable
hop latency), "double" factors the flat mesh inter-major and times the
prefetched inter hop in its floor.

--wire-dtype int8|fp8 runs both ring legs with the wire-precision layer
(cfg.wire_dtype: rotating payloads quantized to 1 byte/element with fp32
per-block scales riding the same slots; docs/fused_ring.md) and times an
additional QUANTIZED comm-only floor per fwd/bwd row (`t_comm_q_s`:
1-byte carriers + the scale sub-payloads, same hop structure).  Every
fwd/bwd row also records `wire_bytes_per_round` — the per-round
per-device ring bytes from schedule.wire_round_bytes, the single
derivation the obs counters and the schedule-replay test share — so the
fp32 vs int8 byte ratio is read straight off the jsonl.

Every row additionally records the STATIC cost model's predicted floors
(analysis/costmodel.py roofline: `t_comm_pred_s`, `t_compute_pred_s`)
and `pred_ratio` (measured fused time over the model's binding floor),
so each TPU window calibrates the model's spec-sheet HW table for free —
the cost-model-consistent lint rule reads TPU rows back and fails when a
measured comm floor drifts outside the model's calibration band.
"""

import argparse
import json
import os
import time

# off-TPU smoke runs need a simulated ring; must be set before jax inits
# (harmless when a real TPU backend is selected)
if os.environ.get("JAX_PLATFORMS", "") == "cpu" or not os.environ.get(
        "JAX_PLATFORMS"):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from benchmarks.benchmark import bench_fn, flops
from burst_attn_tpu.parallel import burst, layouts
from burst_attn_tpu.parallel.ring import ppermute_next
from burst_attn_tpu.utils.compat import shard_map


def _mesh(world):
    devs = jax.devices()
    if len(devs) < world:
        raise SystemExit(f"need {world} devices, have {len(devs)}")
    return Mesh(np.array(devs[:world]), ("sp",))


def _shard_fwd(mesh, cfg, no_rotate=False, n_rounds=None):
    """Shard-level forward launcher; no_rotate=True swaps every ring
    rotation for a no-op (the compute-only floor: same rounds, same tile
    kernels, the resident chunk stands in for every arriving chunk).
    n_rounds overrides the floor's round count — the occupancy-elided
    schedule's compute floor is r_live rounds, not the full ring."""
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")

    def f(q, k, v):
        if not no_rotate:
            o, lse = burst._fwd_impl(q, k, v, cfg)
            return jnp.sum(o.astype(jnp.float32)) + jnp.sum(lse)
        # compute-only: W self-spec rounds against the resident chunk
        from burst_attn_tpu.ops.masks import round_spec
        from burst_attn_tpu.parallel.ring import my_partition
        from burst_attn_tpu.utils.compat import axis_size

        world = n_rounds or axis_size(cfg.intra_axis)
        me = my_partition(cfg.intra_axis, None)
        s = q.shape[2]
        spec = round_spec(me, me, s, s, cfg.causal, cfg.layout)
        st = burst._tile_fwd(cfg, q, k, v, None, None, None,
                             q.shape[3] ** -0.5, spec, triangular=cfg.causal)
        for _ in range(world - 1):
            st = burst._tile_fwd(cfg, q, k, v, *st, q.shape[3] ** -0.5, spec,
                                 triangular=cfg.causal)
        m, lse, acc = st
        return jnp.sum(acc.astype(jnp.float32)) + jnp.sum(lse)

    fn = shard_map(f, mesh=mesh, in_specs=(spec4,) * 3, out_specs=P(),
                   check_vma=False)
    return jax.jit(lambda q, k, v: fn(q, k, v))


def _comm_only(mesh, world, topology="uni", factor=None, n_rounds=None,
               wire=None):
    """Comm-only floor of one forward topology, no compute.

    n_rounds truncates the uni rotation count to an occupancy-elided
    schedule's r_live (r_live - 1 hops: the elided program never sends the
    dead rounds' chunks at all).

    wire ("int8" | "fp8") is the QUANTIZED floor (t_comm_q_s): the k/v
    payload rotates as 1-byte carriers (int8 and fp8 both ship 1 B/elem)
    plus the two per-(batch, kv head) fp32 scale sub-payloads the fused
    kernels send down the same slots — schedule.wire_round_bytes' fwd
    accounting.  The quantize cast happens once inside the program, like
    the real entry's quantize-once-at-entry.

    uni     W-1 full-payload rotations of the (k, v) pair.
    bidi    the counter-rotating split: each round moves HALF the payload
            clockwise and half counter-clockwise concurrently, for
            max(ceil, floor)((W-1)/2) rounds — both ICI directions carry
            traffic at once, so on a comm-bound ring this floor is the
            headroom the bidirectional schedule can claim.  The (tiny)
            scale stream rides clockwise.
    double  factored (n_inter, n_intra): per cycle, n_intra-1 intra unit
            hops plus (except the last cycle) one inter hop of n_intra
            positions along the flat axis.
    """
    spec4 = P(None, None, "sp", None)

    def rot(t, hops):
        from burst_attn_tpu.utils.compat import axis_size
        import jax.lax as lax

        n = axis_size("sp")
        perm = [(i, (i + hops) % n) for i in range(n)]
        return jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, "sp", perm), t)

    def f(k, v):
        kv = (k, v)
        scales = ()
        if wire is not None:
            kv = tuple(t.astype(jnp.int8) for t in kv)
            scales = (jnp.zeros((k.shape[0], k.shape[1], 1, 1),
                                jnp.float32),) * 2
        if topology == "bidi":
            h_cw = (world - 1 + 1) // 2
            h_ccw = (world - 1) // 2
            half = k.shape[2] // 2
            cw = tuple(t[:, :, :half] for t in kv)
            ccw = tuple(t[:, :, half:] for t in kv)
            for j in range(max(h_cw, h_ccw)):
                if j < h_cw:
                    cw = rot(cw, 1)
                    scales = rot(scales, 1)
                if j < h_ccw:
                    ccw = rot(ccw, -1)
            return sum(jnp.sum(t.astype(jnp.float32))
                       for t in cw + ccw + scales)
        if topology == "double":
            n_i, n_s = factor
            acc = jnp.float32(0.0)
            for c in range(n_i):
                for _ in range(n_s - 1):
                    kv = rot(kv, 1)
                    scales = rot(scales, 1)
                if c < n_i - 1:
                    kv = rot(kv, n_s)  # the prefetched inter hop
                    scales = rot(scales, n_s)
                acc = acc + jnp.sum(kv[0].astype(jnp.float32))
            return acc + sum(jnp.sum(t.astype(jnp.float32))
                             for t in kv[1:] + scales)
        for _ in range((n_rounds or world) - 1):
            kv = ppermute_next(kv, "sp")
            scales = ppermute_next(scales, "sp")
        return sum(jnp.sum(t.astype(jnp.float32)) for t in kv + scales)

    fn = shard_map(f, mesh=mesh, in_specs=(spec4,) * 2, out_specs=P(),
                   check_vma=False)
    return jax.jit(lambda k, v: fn(k, v))


def _shard_fwd_residuals(mesh, cfg):
    """(o, lse) of the scan forward — the residuals both backward legs
    consume, computed once per config outside the timed region."""
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")
    fn = shard_map(lambda q, k, v: burst._fwd_impl(q, k, v, cfg),
                   mesh=mesh, in_specs=(spec4,) * 3,
                   out_specs=(spec4, spec3), check_vma=False)
    return jax.jit(fn)


def _shard_bwd(mesh, cfg, no_rotate=False, n_rounds=None):
    """Shard-level backward launcher; no_rotate=True swaps both rotating
    streams for no-ops (the compute-only floor: same W rounds of tile_bwd
    against the resident bundle, zero inter-chip traffic)."""
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")

    def f(q, k, v, o, lse, do):
        if not no_rotate:
            dq, dk, dv = burst._bwd_impl(cfg, q, k, v, o, lse, do)
            return (jnp.sum(dq) + jnp.sum(dk) + jnp.sum(dv)).astype(
                jnp.float32)
        from burst_attn_tpu.ops.masks import round_spec
        from burst_attn_tpu.parallel.ring import my_partition
        from burst_attn_tpu.utils.compat import axis_size

        world = n_rounds or axis_size(cfg.intra_axis)
        me = my_partition(cfg.intra_axis, None)
        s = q.shape[2]
        scale = q.shape[3] ** -0.5
        spec = round_spec(me, me, s, s, cfg.causal, cfg.layout)
        delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                        axis=-1)
        acc = jnp.float32(0.0)
        for _ in range(world):
            dq, dk, dv = burst._tile_bwd(cfg, do, q, k, v, delta, lse,
                                         scale, spec)
            acc = acc + jnp.sum(dq) + jnp.sum(dk) + jnp.sum(dv)
        return acc

    fn = shard_map(f, mesh=mesh, in_specs=(spec4,) * 4 + (spec3, spec4),
                   out_specs=P(), check_vma=False)
    return jax.jit(lambda *a: fn(*a))


def _comm_only_bwd(mesh, world, opt_comm, n_rounds=None, wire=None):
    """Comm-only backward floor: W-1 rotations of the 4-operand q-side
    bundle (delta|o, do, q, lse) plus the dq ring's W add-and-forward hops
    (W-1 in-ring + the return-home hop), no compute.  n_rounds truncates
    both streams to an elided schedule's r_live (the dq return-home hop
    always remains).

    wire ("int8" | "fp8") is the QUANTIZED floor (t_comm_q_s): the
    bundle's (delta|o, do, q) rotate as 1-byte carriers with three
    per-(batch, head) fp32 scale scalars riding along (lse stays fp32,
    exempt from quantization), and the dq stream moves 1 byte/element
    plus its per-hop refreshed scale — schedule.wire_round_bytes' bwd
    accounting."""
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")
    first_spec = spec3 if opt_comm else spec4

    def f(first, do, q, lse):
        pay = (first, do, q, lse)
        if wire is not None:
            sc = jnp.zeros((q.shape[0], q.shape[1], 1, 1), jnp.float32)
            pay = tuple(t.astype(jnp.int8) for t in (first, do, q)) \
                + (lse, sc, sc, sc)
            dqs = (jnp.zeros(q.shape, jnp.int8), sc)
        else:
            dqs = (jnp.zeros(q.shape, jnp.float32),)
        for _ in range((n_rounds or world) - 1):
            pay = ppermute_next(pay, "sp")
            dqs = ppermute_next(dqs, "sp")
        dqs = ppermute_next(dqs, "sp")  # return-home hop
        return sum(jnp.sum(t.astype(jnp.float32)) for t in pay + dqs)

    fn = shard_map(f, mesh=mesh,
                   in_specs=(first_spec, spec4, spec4, spec3),
                   out_specs=P(), check_vma=False)
    return jax.jit(lambda *a: fn(*a))


def _shard_fwdbwd(mesh, cfg):
    """value_and_grad through the shard-level custom_vjp — both passes of
    one training-step attention in one timed program."""
    spec4 = P(None, None, "sp", None)

    def f(q, k, v, do):
        def loss(q, k, v):
            o = burst.burst_attn_shard(q, k, v, cfg)
            return jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32))

        l, grads = jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
        return l + sum(jnp.sum(g.astype(jnp.float32)) for g in grads)

    fn = shard_map(f, mesh=mesh, in_specs=(spec4,) * 4, out_specs=P(),
                   check_vma=False)
    return jax.jit(lambda *a: fn(*a))


def run_config(seq, world, layout, n, d, causal, out_path, pass_="fwd",
               topology="uni", window=None, wire_dtype="fp32"):
    on_tpu = jax.default_backend() == "tpu"
    wire = None if wire_dtype in (None, "fp32") else wire_dtype
    mesh = _mesh(world)
    # --window W: occupancy-elided schedule (contig causal band).  Both ring
    # legs dispatch the elided program; the floors are measured twice —
    # r_live rounds/hops (what the elided schedule actually moves and
    # computes) AND the dense full-ring floors, so the jsonl row shows the
    # comm+compute the elision removed, not just the end-to-end time.
    r_live = None
    if window is not None:
        from burst_attn_tpu.ops.masks import live_round_prefix

        if layout != "contig" or not causal:
            raise SystemExit("--window needs --layout contig and causal")
        r_live = live_round_prefix("contig", seq // world, world,
                                   causal=True, window=window)
    # topology -> fused-dispatch config + the factored double-ring shape
    factor = None
    topo_kw = {}
    if topology == "bidi":
        topo_kw = {"fused_topology": "bidi"}
    elif topology == "double":
        n_i = 2
        while world % n_i or (world // n_i) < 2:
            n_i += 1
            if n_i > world // 2:
                raise SystemExit(f"--topology double needs a composite "
                                 f"mesh, got {world}")
        factor = (n_i, world // n_i)
        topo_kw = {"fused_seq_factor": factor}
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    key = jax.random.PRNGKey(0)
    kq, kk, kv, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (1, n, seq, d), dtype)
    k = jax.random.normal(kk, (1, n, seq, d), dtype)
    v = jax.random.normal(kv, (1, n, seq, d), dtype)
    do = jax.random.normal(kg, (1, n, seq, d), dtype)
    q, k, v, do = (layouts.to_layout(t, layout, world, 2)
                   for t in (q, k, v, do))

    tile_backend = "pallas" if on_tpu else "jnp"
    win_kw = {} if window is None else {"window": window}
    scan_cfg = burst.BurstConfig(causal=causal, layout=layout,
                                 intra_axis="sp", backend=tile_backend,
                                 wire_dtype=wire, **win_kw)
    fused_cfg = burst.BurstConfig(causal=causal, layout=layout,
                                  intra_axis="sp", backend="fused_ring",
                                  wire_dtype=wire, **topo_kw, **win_kw)

    bench_kw = dict(warmup=2, iters=3, reps=2) if not on_tpu else {}
    os.environ["BURST_FUSED_INTERPRET"] = "1"  # fused legs off-TPU
    dir_floors = {}
    if pass_ == "fwd":
        t_scan = bench_fn(_shard_fwd(mesh, scan_cfg), q, k, v, **bench_kw)
        t_fused = bench_fn(_shard_fwd(mesh, fused_cfg), q, k, v, **bench_kw)
        t_compute = bench_fn(
            _shard_fwd(mesh, scan_cfg, no_rotate=True, n_rounds=r_live),
            q, k, v, **bench_kw)
        t_comm = bench_fn(
            _comm_only(mesh, world, topology, factor, n_rounds=r_live),
            k, v, **bench_kw)
        if r_live is not None:
            # the dense floors: what a non-elided schedule would move
            dir_floors["t_compute_dense_s"] = round(bench_fn(
                _shard_fwd(mesh, scan_cfg, no_rotate=True),
                q, k, v, **bench_kw), 6)
            dir_floors["t_comm_dense_s"] = round(bench_fn(
                _comm_only(mesh, world, topology, factor),
                k, v, **bench_kw), 6)
        if wire is not None:
            dir_floors["t_comm_q_s"] = round(bench_fn(
                _comm_only(mesh, world, topology, factor, n_rounds=r_live,
                           wire=wire),
                k, v, **bench_kw), 6)
        if topology == "bidi":
            # per-direction floors: what each ICI direction costs alone —
            # the gap between t_comm_uni and t_comm is the latency the
            # counter-rotating split reclaims on comm-bound configs
            dir_floors["t_comm_uni_s"] = round(
                bench_fn(_comm_only(mesh, world), k, v, **bench_kw), 6)
            dir_floors["dir_hops"] = {"cw": (world - 1 + 1) // 2,
                                      "ccw": (world - 1) // 2}
        elif topology == "double":
            dir_floors["dir_hops"] = {"intra": factor[0] * (factor[1] - 1),
                                      "inter": factor[0] - 1}
    elif pass_ == "bwd":
        # residuals once, outside the timed region — both legs consume the
        # identical (o, lse)
        o, lse = jax.block_until_ready(
            _shard_fwd_residuals(mesh, scan_cfg)(q, k, v))
        t_scan = bench_fn(_shard_bwd(mesh, scan_cfg), q, k, v, o, lse, do,
                          **bench_kw)
        t_fused = bench_fn(_shard_bwd(mesh, fused_cfg), q, k, v, o, lse, do,
                           **bench_kw)
        t_compute = bench_fn(
            _shard_bwd(mesh, scan_cfg, no_rotate=True, n_rounds=r_live),
            q, k, v, o, lse, do, **bench_kw)
        delta_or_o = (jnp.sum(o.astype(jnp.float32)
                              * do.astype(jnp.float32), axis=-1)
                      if scan_cfg.optimize_bwd_comm else o)
        t_comm = bench_fn(
            _comm_only_bwd(mesh, world, scan_cfg.optimize_bwd_comm,
                           n_rounds=r_live),
            delta_or_o, do, q, lse.astype(jnp.float32), **bench_kw)
        if wire is not None:
            dir_floors["t_comm_q_s"] = round(bench_fn(
                _comm_only_bwd(mesh, world, scan_cfg.optimize_bwd_comm,
                               n_rounds=r_live, wire=wire),
                delta_or_o, do, q, lse.astype(jnp.float32), **bench_kw), 6)
        if r_live is not None:
            dir_floors["t_compute_dense_s"] = round(bench_fn(
                _shard_bwd(mesh, scan_cfg, no_rotate=True),
                q, k, v, o, lse, do, **bench_kw), 6)
            dir_floors["t_comm_dense_s"] = round(bench_fn(
                _comm_only_bwd(mesh, world, scan_cfg.optimize_bwd_comm),
                delta_or_o, do, q, lse.astype(jnp.float32), **bench_kw), 6)
    elif pass_ == "fwd+bwd":
        # one value_and_grad program per backend; floors are the sum of the
        # per-pass floors, so none are (re)measured here
        t_scan = bench_fn(_shard_fwdbwd(mesh, scan_cfg), q, k, v, do,
                          **bench_kw)
        t_fused = bench_fn(_shard_fwdbwd(mesh, fused_cfg), q, k, v, do,
                           **bench_kw)
        t_compute = t_comm = None
    else:
        raise SystemExit(f"unknown --pass {pass_!r}")

    def overlap(t_ring):
        lo = min(t_compute, t_comm)
        if lo <= 0:
            return 0.0
        return max(0.0, min(1.0, (t_compute + t_comm - t_ring) / lo))

    mode = {"fwd": "fwd", "bwd": "bwd", "fwd+bwd": "fwd_bwd"}[pass_]
    pass_f = flops(1, seq, n, d, mode=mode, causal=causal)
    rec = {
        "bench": "ring_overlap",
        "backend": jax.default_backend(),
        "pass": pass_,
        "topology": topology,
        "seq": seq, "world": world, "layout": layout, "heads": n, "dim": d,
        "causal": causal,
        "wire_dtype": wire_dtype,
        **({} if window is None else {"window": window, "r_live": r_live}),
        **dir_floors,
        "t_scan_s": round(t_scan, 6),
        "t_fused_s": round(t_fused, 6),
        "fused_speedup": round(t_scan / t_fused, 4),
        "tflops_scan": round(pass_f / t_scan / 1e12 / world, 2),
        "tflops_fused": round(pass_f / t_fused / 1e12 / world, 2),
        "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if pass_ in ("fwd", "bwd"):
        # per-round per-device ring bytes from the shared derivation
        # (schedule.wire_round_bytes) — what the fp32-vs-int8 acceptance
        # ratio is read from; streams broken out beside the total
        from burst_attn_tpu.parallel import schedule as sched

        wb = sched.wire_round_bytes(
            pass_, wire, b=1, n=n, n_kv=n, s=seq // world, d=d,
            opt_comm=scan_cfg.optimize_bwd_comm,
            itemsize=jnp.dtype(dtype).itemsize)
        rec["wire_bytes_per_round"] = int(sum(wb.values()))
        rec["wire_round_bytes"] = {kk_: int(vv_) for kk_, vv_ in wb.items()}
    if t_compute is not None:
        rec.update({
            "t_compute_only_s": round(t_compute, 6),
            "t_comm_only_s": round(t_comm, 6),
            "overlap_scan": round(overlap(t_scan), 4),
            "overlap_fused": round(overlap(t_fused), 4),
            "ring_vs_floor_scan": round(t_scan / max(t_compute, t_comm), 4),
            "ring_vs_floor_fused": round(t_fused / max(t_compute, t_comm), 4),
        })
    # the static cost model's predicted floors (analysis/costmodel.py)
    # beside the measured ones: every TPU row calibrates the roofline's
    # spec-sheet HW table for free (the cost-model-consistent lint rule
    # reads these rows back), and pred_ratio is the measured-over-model
    # correction factor.  Best-effort: the benchmark never fails on the
    # model — a row without pred fields is a model bug to chase, not a
    # lost measurement.
    try:
        from burst_attn_tpu.analysis import costmodel

        pred_passes = ("fwd", "bwd") if pass_ == "fwd+bwd" else (pass_,)
        t_comm_pred = t_compute_pred = 0.0
        for p_ in pred_passes:
            tc_, tx_ = costmodel.predict_floors(
                p_, b=1, n=n, n_kv=n, s=seq // world, d=d, world=world,
                topology=topology, wire=wire, layout=layout,
                causal=causal, window=window,
                opt_comm=scan_cfg.optimize_bwd_comm,
                itemsize=jnp.dtype(dtype).itemsize)
            t_comm_pred += tc_
            t_compute_pred += tx_
        # ns precision: CPU smoke shapes have sub-microsecond model floors
        rec.update({
            "t_comm_pred_s": round(t_comm_pred, 9),
            "t_compute_pred_s": round(t_compute_pred, 9),
            "pred_ratio": round(
                t_fused / max(t_comm_pred, t_compute_pred), 4),
        })
    except Exception as e:  # noqa: BLE001 — keep the measurement
        rec["pred_error"] = f"{type(e).__name__}: {e}"
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    print(json.dumps(rec))
    # mirror the headline quantities into the obs registry so the overlap
    # numbers show up in `python -m burst_attn_tpu.obs` next to the ring
    # dispatch counters the measured programs just advanced
    from burst_attn_tpu import obs

    labels = {"seq": seq, "world": world, "layout": layout, "pass": pass_,
              "topology": topology, "wire": wire_dtype}
    for key in ("overlap_scan", "overlap_fused", "fused_speedup",
                "tflops_scan", "tflops_fused"):
        if key in rec:
            obs.gauge(f"bench.ring_overlap.{key}").set(rec[key], **labels)
    obs.counter("bench.ring_overlap_runs").inc(**{"pass": pass_})
    return rec


def main():
    ap = argparse.ArgumentParser()
    on_tpu = jax.default_backend() == "tpu"
    ap.add_argument("--seqs", default="16384,65536" if on_tpu else "128")
    ap.add_argument("--mesh", type=int, default=8 if on_tpu else 4)
    ap.add_argument("--layout", default="zigzag")
    ap.add_argument("--heads", type=int, default=32 if on_tpu else 2)
    ap.add_argument("--dim", type=int, default=128 if on_tpu else 16)
    ap.add_argument("--noncausal", action="store_true")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window width: dispatch the occupancy-"
                         "elided contig schedule and record its r_live "
                         "floors next to the dense ones (needs --layout "
                         "contig)")
    ap.add_argument("--pass", dest="pass_", default="fwd",
                    choices=["fwd", "bwd", "fwd+bwd", "all"],
                    help="which pass(es) to measure; 'all' runs the three "
                         "modes back to back per seq")
    ap.add_argument("--topology", default="uni",
                    choices=["uni", "bidi", "double", "all"],
                    help="fused-ring schedule topology (parallel/schedule."
                         "py); bidi records per-direction comm floors, "
                         "double factors the flat mesh inter-major; 'all' "
                         "sweeps the three")
    ap.add_argument("--wire-dtype", default="fp32",
                    choices=["fp32", "int8", "fp8"],
                    help="wire precision for the rotating payloads "
                         "(cfg.wire_dtype): int8/fp8 run both ring legs "
                         "quantized and add the t_comm_q_s quantized comm "
                         "floor; every fwd/bwd row records "
                         "wire_bytes_per_round either way")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "ring_overlap.jsonl"))
    args = ap.parse_args()
    passes = (["fwd", "bwd", "fwd+bwd"] if args.pass_ == "all"
              else [args.pass_])
    topologies = (["uni", "bidi", "double"] if args.topology == "all"
                  else [args.topology])
    if args.window is not None and args.layout != "contig":
        # the band structure only exists in natural token order
        print("note: --window implies --layout contig")
        args.layout = "contig"
    for seq in [int(s) for s in args.seqs.split(",")]:
        for topo in topologies:
            for p in passes:
                run_config(seq, args.mesh, args.layout, args.heads,
                           args.dim, not args.noncausal, args.out,
                           pass_=p, topology=topo, window=args.window,
                           wire_dtype=args.wire_dtype)
    # one obs export per invocation, beside the jsonl results
    from burst_attn_tpu import obs

    obs.export_jsonl(os.path.join(os.path.dirname(args.out), "obs.jsonl"))


if __name__ == "__main__":
    main()
