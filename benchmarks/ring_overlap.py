"""Ring-overlap microbenchmark: scan+ppermute ring vs the fused RDMA kernel.

Measures, per (seq, layout) config on the real ring mesh:

  t_scan     — the scan-based ring forward (`backend="pallas"` per-round
               pallas_call + lax.ppermute; overlap is whatever XLA's async
               collective scheduling achieves)
  t_fused    — the fused single-kernel ring (`backend="fused_ring"`,
               in-kernel RDMA KV rotation, ops/fused_ring.py)
  t_compute  — compute-only floor: the same W rounds of tile compute with
               the ring rotation REMOVED (every round re-reads the resident
               local KV; identical kernel launches, masks and state carry,
               zero inter-chip traffic)
  t_comm     — comm-only floor: just the W-1 KV rotations (ppermute of the
               k/v payload, no attention compute)

and derives the achieved overlap fraction

  overlap = (t_compute + t_comm - t_ring) / min(t_compute, t_comm)

(1.0 = the smaller phase is fully hidden behind the larger; 0.0 = fully
serialized), plus the ideal-floor ratio t_ring / max(t_compute, t_comm).
One JSON line per config appends to results/ring_overlap.jsonl.

On a CPU host this still runs a tiny smoke config through the interpreted
fused kernel (BURST_FUSED_INTERPRET=1 is set for the fused leg) so the
harness itself is testable anywhere; the numbers are only meaningful on a
TPU ring.

Usage:  python -m benchmarks.ring_overlap [--seqs 16384,65536]
        [--mesh 8] [--layout zigzag] [--heads 32] [--dim 128]
        [--out results/ring_overlap.jsonl]
"""

import argparse
import json
import os
import time

# off-TPU smoke runs need a simulated ring; must be set before jax inits
# (harmless when a real TPU backend is selected)
if os.environ.get("JAX_PLATFORMS", "") == "cpu" or not os.environ.get(
        "JAX_PLATFORMS"):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from benchmarks.benchmark import bench_fn, flops
from burst_attn_tpu.parallel import burst, layouts
from burst_attn_tpu.parallel.ring import ppermute_next
from burst_attn_tpu.utils.compat import shard_map


def _mesh(world):
    devs = jax.devices()
    if len(devs) < world:
        raise SystemExit(f"need {world} devices, have {len(devs)}")
    return Mesh(np.array(devs[:world]), ("sp",))


def _shard_fwd(mesh, cfg, no_rotate=False):
    """Shard-level forward launcher; no_rotate=True swaps every ring
    rotation for a no-op (the compute-only floor: same rounds, same tile
    kernels, the resident chunk stands in for every arriving chunk)."""
    spec4 = P(None, None, "sp", None)
    spec3 = P(None, None, "sp")

    def f(q, k, v):
        if not no_rotate:
            o, lse = burst._fwd_impl(q, k, v, cfg)
            return jnp.sum(o.astype(jnp.float32)) + jnp.sum(lse)
        # compute-only: W self-spec rounds against the resident chunk
        from burst_attn_tpu.ops.masks import round_spec
        from burst_attn_tpu.parallel.ring import my_partition
        from burst_attn_tpu.utils.compat import axis_size

        world = axis_size(cfg.intra_axis)
        me = my_partition(cfg.intra_axis, None)
        s = q.shape[2]
        spec = round_spec(me, me, s, s, cfg.causal, cfg.layout)
        st = burst._tile_fwd(cfg, q, k, v, None, None, None,
                             q.shape[3] ** -0.5, spec, triangular=cfg.causal)
        for _ in range(world - 1):
            st = burst._tile_fwd(cfg, q, k, v, *st, q.shape[3] ** -0.5, spec,
                                 triangular=cfg.causal)
        m, lse, acc = st
        return jnp.sum(acc.astype(jnp.float32)) + jnp.sum(lse)

    fn = shard_map(f, mesh=mesh, in_specs=(spec4,) * 3, out_specs=P(),
                   check_vma=False)
    return jax.jit(lambda q, k, v: fn(q, k, v))


def _comm_only(mesh, world):
    """W-1 payload rotations of the (k, v) pair, no compute."""
    spec4 = P(None, None, "sp", None)

    def f(k, v):
        kv = (k, v)
        for _ in range(world - 1):
            kv = ppermute_next(kv, "sp")
        return jnp.sum(kv[0].astype(jnp.float32)) + jnp.sum(
            kv[1].astype(jnp.float32))

    fn = shard_map(f, mesh=mesh, in_specs=(spec4,) * 2, out_specs=P(),
                   check_vma=False)
    return jax.jit(lambda k, v: fn(k, v))


def run_config(seq, world, layout, n, d, causal, out_path):
    on_tpu = jax.default_backend() == "tpu"
    mesh = _mesh(world)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, n, seq, d), dtype)
    k = jax.random.normal(kk, (1, n, seq, d), dtype)
    v = jax.random.normal(kv, (1, n, seq, d), dtype)
    q, k, v = (layouts.to_layout(t, layout, world, 2) for t in (q, k, v))

    tile_backend = "pallas" if on_tpu else "jnp"
    scan_cfg = burst.BurstConfig(causal=causal, layout=layout,
                                 intra_axis="sp", backend=tile_backend)
    fused_cfg = burst.BurstConfig(causal=causal, layout=layout,
                                  intra_axis="sp", backend="fused_ring")

    bench_kw = dict(warmup=2, iters=3, reps=2) if not on_tpu else {}
    t_scan = bench_fn(_shard_fwd(mesh, scan_cfg), q, k, v, **bench_kw)
    os.environ["BURST_FUSED_INTERPRET"] = "1"  # fused leg off-TPU
    t_fused = bench_fn(_shard_fwd(mesh, fused_cfg), q, k, v, **bench_kw)
    t_compute = bench_fn(_shard_fwd(mesh, scan_cfg, no_rotate=True), q, k, v,
                         **bench_kw)
    t_comm = bench_fn(_comm_only(mesh, world), k, v, **bench_kw)

    def overlap(t_ring):
        lo = min(t_compute, t_comm)
        if lo <= 0:
            return 0.0
        return max(0.0, min(1.0, (t_compute + t_comm - t_ring) / lo))

    fwd_f = flops(1, seq, n, d, mode="fwd", causal=causal)
    rec = {
        "bench": "ring_overlap",
        "backend": jax.default_backend(),
        "seq": seq, "world": world, "layout": layout, "heads": n, "dim": d,
        "causal": causal,
        "t_scan_s": round(t_scan, 6),
        "t_fused_s": round(t_fused, 6),
        "t_compute_only_s": round(t_compute, 6),
        "t_comm_only_s": round(t_comm, 6),
        "overlap_scan": round(overlap(t_scan), 4),
        "overlap_fused": round(overlap(t_fused), 4),
        "ring_vs_floor_scan": round(t_scan / max(t_compute, t_comm), 4),
        "ring_vs_floor_fused": round(t_fused / max(t_compute, t_comm), 4),
        "fused_speedup": round(t_scan / t_fused, 4),
        "tflops_scan": round(fwd_f / t_scan / 1e12 / world, 2),
        "tflops_fused": round(fwd_f / t_fused / 1e12 / world, 2),
        "ts_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    print(json.dumps(rec))
    # mirror the headline quantities into the obs registry so the overlap
    # numbers show up in `python -m burst_attn_tpu.obs` next to the ring
    # dispatch counters the measured programs just advanced
    from burst_attn_tpu import obs

    labels = dict(seq=seq, world=world, layout=layout)
    for key in ("overlap_scan", "overlap_fused", "fused_speedup",
                "tflops_scan", "tflops_fused"):
        obs.gauge(f"bench.ring_overlap.{key}").set(rec[key], **labels)
    obs.counter("bench.ring_overlap_runs").inc()
    return rec


def main():
    ap = argparse.ArgumentParser()
    on_tpu = jax.default_backend() == "tpu"
    ap.add_argument("--seqs", default="16384,65536" if on_tpu else "128")
    ap.add_argument("--mesh", type=int, default=8 if on_tpu else 4)
    ap.add_argument("--layout", default="zigzag")
    ap.add_argument("--heads", type=int, default=32 if on_tpu else 2)
    ap.add_argument("--dim", type=int, default=128 if on_tpu else 16)
    ap.add_argument("--noncausal", action="store_true")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "ring_overlap.jsonl"))
    args = ap.parse_args()
    for seq in [int(s) for s in args.seqs.split(",")]:
        run_config(seq, args.mesh, args.layout, args.heads, args.dim,
                   not args.noncausal, args.out)
    # one obs export per invocation, beside the jsonl results
    from burst_attn_tpu import obs

    obs.export_jsonl(os.path.join(os.path.dirname(args.out), "obs.jsonl"))


if __name__ == "__main__":
    main()
