"""Batch-scaling probe (round-2 verdict item 3): same-FLOPs configs lose
~25% per-chip throughput as batch count rises (results/results_scaling.jsonl:
fwd 158.4 @ b=1/64K -> 117.0 @ b=4/32K; the reference instead RISES with
batch, reference README.md:100-103).

Per-step arithmetic from round 2: 13.1us (b=1, 64K) -> 14.1 (b=2, 32K) ->
17.3 (b=4, 32K) with IDENTICAL 2048x2048 blocks — per-step cost grows with
batch count / shrinking per-entry rows.  Candidate causes this probe
separates:

  * batch-count term: b=1 vs b=2 vs b=4 at FIXED seq=32K (same per-entry
    grid, same per-step work; flat TFLOPs/s here acquits the batch dim)
  * row-length term: the tri grid's init/finalize steps (_read_rows /
    _write_rows state repacking) are a 4/(nqb+1) fraction of all steps —
    nqb=16 at 32K pays 23.5%, nqb=32 at 64K pays 12%
  * grid-geometry term: tri vs rect (BURST_NO_TRI) at the same configs
    (the rect grid has uniform init/fin density by construction)
  * block-size term: bq=1024 at 32K restores nqb=32 (the 64K init/fin
    density) at 4x the step count

Writes one jsonl row per config to --out; run on a real chip:

    python -m benchmarks.batch_probe --out results/batch_probe.jsonl
"""

import argparse
import json
import os
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--out", default="results/batch_probe.jsonl")
    ap.add_argument("--trace-dir", default="",
                    help="capture an XLA trace of the worst config")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from benchmarks.benchmark import bench_fn, flops

    if jax.default_backend() != "tpu":
        print("batch_probe: not on TPU; refusing to record numbers",
              file=sys.stderr)
        sys.exit(1)

    from burst_attn_tpu.ops.pallas_flash import flash_attention

    n, d = args.heads, args.dim
    if os.environ.get("BURST_NO_TRI", "").strip().lower() not in ("", "0", "false"):
        # _tri_disabled() is read at trace time: with the env var exported
        # the "tri" rows would silently compile rect grids and the per-step
        # arithmetic would be ~2x off.  The probe owns this knob.
        sys.exit("batch_probe: unset BURST_NO_TRI first (the probe toggles "
                 "it per case and needs both grids)")
    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)

    def record(row):
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)

    # (batch, seq, block_q or (block_q, block_kv), no_tri)
    cases = [
        (1, 65536, None, False),   # round-2 anchor: 158.4
        (1, 32768, None, False),   # NEW: batch-free seq term
        (2, 32768, None, False),   # round-2: 143.1
        (4, 32768, None, False),   # round-2: 117.0
        (4, 32768, None, True),    # rect grid: uniform init/fin density
        (1, 32768, 1024, False),   # nqb=32 at 32K: 64K's init/fin fraction
        (4, 32768, 1024, False),
        (8, 16384, None, False),   # extreme: nqb=8, 4/9 steps init/fin
        # tall-q tri grid (round 4): same area/step count, init/fin events
        # drop to 4/((nqb+1)r) of steps and K/V bytes to 1/r — the fix
        # candidate for the regression if the init/fin term is convicted
        (4, 32768, (4096, 1024), False),
        (1, 65536, (4096, 1024), False),
        (8, 16384, (4096, 1024), False),
    ]

    def run_ablate(b, s):
        """nosoftmax ablation at batch b (discriminator: if the batch
        regression SURVIVES with the whole VPU softmax chain stripped,
        it is grid/DMA-side — per-step overhead, megacore, state blocks —
        not VPU scheduling).  Timing scaffold shared with sweep_blocks
        (benchmarks.benchmark.time_flash_fwd)."""
        from benchmarks.benchmark import time_flash_fwd

        try:
            t, tf = time_flash_fwd(b, n, s, d, block_q=2048, block_kv=2048,
                                   block_kv_compute=1024,
                                   _ablate="nosoftmax")
            record({"batch": b, "seq": s, "block_q": 2048, "grid": "tri",
                    "ablate": "nosoftmax", "ms": round(t * 1e3, 2),
                    "tflops": round(tf, 1)})
        except Exception as e:  # noqa: BLE001
            record({"batch": b, "seq": s, "ablate": "nosoftmax",
                    "error": f"{type(e).__name__}: {e}"[:200]})


    for b, s, bq, no_tri in cases:
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, n, s, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, n, s, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, n, s, d), jnp.bfloat16)
        if no_tri:
            os.environ["BURST_NO_TRI"] = "1"
        bq_eff, bkv_eff = (bq if isinstance(bq, tuple) else (bq or 2048,
                                                             bq or 2048))
        try:
            f = jax.jit(lambda q, k, v, bq=bq_eff, bkv=bkv_eff: jnp.sum(
                flash_attention(q, k, v, None, True, bq, bkv)
                .astype(jnp.float32)))
            t = bench_fn(f, q, k, v)
            fl = flops(b, s, n, d, "fwd", True)
            # tri-grid step count: b*n * (nqb/2) * (nqb+1)*r, r = bq/bkv
            nqb = s // bq_eff
            r = bq_eff // bkv_eff
            steps = b * n * (nqb // 2) * (nqb + 1) * r if not no_tri else (
                b * n * nqb * nqb * r)
            record({"batch": b, "seq": s, "block_q": bq_eff,
                    "block_kv": bkv_eff,
                    "grid": "rect" if no_tri else "tri",
                    "ms": round(t * 1e3, 2),
                    "tflops": round(fl / t / 1e12, 1),
                    "us_per_step": round(t * 1e6 / steps, 2),
                    "initfin_frac": round(4 / ((nqb + 1) * r), 3)})
        except Exception as e:  # noqa: BLE001 — record and continue
            record({"batch": b, "seq": s, "block_q": bq_eff,
                    "block_kv": bkv_eff,
                    "grid": "rect" if no_tri else "tri",
                    "error": f"{type(e).__name__}: {e}"[:200]})
        finally:
            if no_tri:
                os.environ.pop("BURST_NO_TRI", None)

    # ablation discriminator AFTER the anchors (a tunnel drop should cost
    # the extras, not the baseline rows)
    run_ablate(1, 32768)
    run_ablate(4, 32768)

    if args.trace_dir:
        b, s = 4, 32768
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, n, s, d), jnp.bfloat16)
        k = jax.random.normal(kk, (b, n, s, d), jnp.bfloat16)
        v = jax.random.normal(kv, (b, n, s, d), jnp.bfloat16)
        f = jax.jit(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, None, True).astype(jnp.float32)))
        float(f(q, k, v))  # compile + warm
        with jax.profiler.trace(args.trace_dir):
            float(f(q, k, v))
        print(f"trace written to {args.trace_dir}", flush=True)


if __name__ == "__main__":
    main()
