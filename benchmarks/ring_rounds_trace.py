"""Single-chip trace of two chained flash_fwd ring rounds (docs §5.1).

A W=1 ring has no permute, but the KERNEL side of the overlap story is
observable on one chip: two back-to-back `flash_fwd` rounds with the
carry-in state are exactly what each device executes per ring round, and
the XProf trace shows whether the second round's DMA warm-up hides behind
the first round's tail (the intra-kernel analogue of the scan-level
overlap the scheduler provides between permute and compute).

    python -m benchmarks.ring_rounds_trace --trace-dir results/trace_rounds
"""

import argparse
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=16384)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--trace-dir", default="results/trace_rounds")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print("ring_rounds_trace: not on TPU; refusing", file=sys.stderr)
        sys.exit(1)

    from burst_attn_tpu.ops.masks import round_spec
    from burst_attn_tpu.ops.pallas_flash import flash_fwd
    from burst_attn_tpu.ops.tile import finalize, init_state

    b, n, s, d = 1, args.heads, args.seq, args.dim
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    q = jax.random.normal(ks[0], (b, n, s, d), jnp.bfloat16)
    k0 = jax.random.normal(ks[1], (b, n, s, d), jnp.bfloat16)
    v0 = jax.random.normal(ks[2], (b, n, s, d), jnp.bfloat16)
    k1 = jax.random.normal(ks[3], (b, n, s, d), jnp.bfloat16)
    v1 = jax.random.normal(ks[4], (b, n, s, d), jnp.bfloat16)
    scale = d**-0.5
    # two rounds as a striped ring sees them: own partition (offset 0) then
    # the neighbor's (offset -1) — both full-window causal tri grids
    spec0 = round_spec(jnp.int32(1), jnp.int32(1), s, s, True, "striped")
    spec1 = round_spec(jnp.int32(1), jnp.int32(0), s, s, True, "striped")

    @jax.jit
    def two_rounds(q, k0, v0, k1, v1):
        st = init_state(b, n, s, d)
        st = flash_fwd(q, k0, v0, *st, scale, spec0, triangular=True)
        st = flash_fwd(q, k1, v1, *st, scale, spec1, triangular=True)
        return jnp.sum(finalize(*st, q.dtype).astype(jnp.float32))

    print(float(two_rounds(q, k0, v0, k1, v1)), flush=True)  # compile+warm
    with jax.profiler.trace(args.trace_dir):
        for _ in range(3):
            r = float(two_rounds(q, k0, v0, k1, v1))
    print(f"trace written to {args.trace_dir} (result {r})", flush=True)


if __name__ == "__main__":
    main()
