"""Root-cause probe for the bkv=4096 VMEM cliff (round-1 verdict item 4).

Round-1 sweeps found a CLIFF, not a slope: fwd 2048x4096 / bwd 2048x2048 /
1024x4096 collapse to ~56-76 TFLOPs/s while 2048x2048 reaches 150+.  The
suspects: (a) Mosaic retiling/layout pathology once the f32 score tile
exceeds some internal budget, (b) VMEM double-buffering pressure forcing
serialization, (c) the compute-sub-block pipeline losing its overlap.

This probe separates them by sweeping the compute sub-block at fixed
memory block (same VMEM residency, different inner tiling) and capturing a
per-config XLA trace: if (a), all bkc settings at bkv=4096 stay slow; if
(b), small bkc recovers; the traces show whether the kernel serializes
against DMA (gaps) or just runs uniformly slower (layout).

    python -m benchmarks.cliff_probe --trace-root cliff_traces

The probe pins BURST_NO_TRI=1 itself (checked at trace time, so an
in-process set works): every config must use the rectangular grid the
round-1 cliff was measured on — the square control would otherwise take
the triangular path while the 4096 configs can't, muddying the comparison.
"""

import argparse
import json
import os
import sys

os.environ["BURST_NO_TRI"] = "1"
# the probe's entire point is to measure past-the-cliff configs: disable
# the tuning-table clamp derived from its own findings
os.environ["BURST_ALLOW_CLIFF"] = "1"


CONFIGS = [
    # (block_q, block_kv, block_kv_compute) — None = kernel default
    (2048, 2048, 1024),   # the v5e optimum (control)
    (2048, 4096, 1024),   # the cliff
    (2048, 4096, 512),    # cliff with smaller compute tile
    (2048, 4096, 2048),   # cliff with bigger compute tile
    (1024, 4096, 1024),   # cliff at half q block
    (2048, 4096, 4096),   # no sub-blocking at all
]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=65536)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--trace-root", default=None,
                    help="capture one XLA trace per config under this dir")
    ap.add_argument("--out", default="results/cliff_probe.jsonl")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print("cliff_probe: not on TPU; refusing to record numbers",
              file=sys.stderr)
        sys.exit(1)

    from benchmarks.benchmark import bench_fn, flops
    from burst_attn_tpu.ops.pallas_flash import flash_attention

    b, n, d, s = 1, args.heads, args.dim, args.seq
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, n, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, n, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, n, s, d), jnp.bfloat16)

    for bq, bkv, bkc in CONFIGS:
        fwd = jax.jit(
            lambda q, k, v, bq=bq, bkv=bkv, bkc=bkc: jnp.sum(
                flash_attention(q, k, v, None, True, bq, bkv,
                                block_kv_compute=bkc).astype(jnp.float32)))
        try:
            t = bench_fn(fwd, q, k, v)
        except Exception as e:  # a config may simply fail to compile
            rec = {"block_q": bq, "block_kv": bkv, "block_kv_compute": bkc,
                   "error": repr(e)[:300]}
            print(json.dumps(rec), flush=True)
            with open(args.out, "a") as f:
                f.write(json.dumps(rec) + "\n")
            continue
        tflops = flops(b, s, n, d, "fwd", True) / t / 1e12
        rec = {"block_q": bq, "block_kv": bkv, "block_kv_compute": bkc,
               "seq": s, "fwd_ms": round(t * 1e3, 3),
               "fwd_tflops": round(tflops, 2), "grid": "rect"}
        if args.trace_root:
            tdir = f"{args.trace_root}/bq{bq}_bkv{bkv}_bkc{bkc}"
            with jax.profiler.trace(tdir):
                float(fwd(q, k, v))
            rec["trace"] = tdir
        print(json.dumps(rec), flush=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
